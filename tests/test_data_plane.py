"""Out-of-core data plane (lightgbm_tpu/data, docs/DATA_PLANE.md):
chunk-store durability red paths, resume-after-crash, streaming
two-pass bit-exactness vs the in-RAM path, prefetch bounds/ordering,
Dask partition spooling, and the unified RAM-budget warning."""

import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data import (
    last_stats,
    reset_stats,
    warn_over_budget,
)
from lightgbm_tpu.data.prefetch import ChunkPrefetcher
from lightgbm_tpu.data.store import (
    ChunkIntegrityError,
    ChunkStore,
    SpooledData,
    spool_numpy,
)

REPO = Path(__file__).resolve().parents[1]


def _xy(rng, n=3000, f=8):
    X = rng.randn(n, f)
    X[:, 2] = (X[:, 2] > 0.3)
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + rng.randn(n) * 0.1
    return X, y


def _strip_data_params(model_text: str) -> str:
    """The chunked run records its extra params in the `parameters:`
    section by definition; everything else must be bit-identical."""
    return "\n".join(
        line for line in model_text.splitlines()
        if not line.startswith(("[data_source", "[ram_budget_mb",
                                "[data_chunk_rows", "[data_spool_dir"))
    )


# ---------------------------------------------------------------- store
def test_store_roundtrip_with_metadata(rng, tmp_path):
    X = rng.randn(700, 5)
    w = rng.rand(700).astype(np.float32)
    store = spool_numpy(X, tmp_path / "s", chunk_rows=256,
                        label=X[:, 0], weight=w)
    assert store.total_rows == 700
    assert store.num_chunks == 3  # 256 + 256 + 188
    assert store.complete
    back = ChunkStore.open(tmp_path / "s")
    rows = []
    for idx, row0, arrays in back.iter_chunks():
        assert row0 == idx * 256
        rows.append(arrays["cols"].T)
    np.testing.assert_array_equal(np.concatenate(rows), X)
    np.testing.assert_array_equal(back.gather_meta("label"),
                                  X[:, 0].astype(np.float64))
    np.testing.assert_allclose(back.gather_meta("weight"), w)


def test_truncated_chunk_fails_loudly(rng, tmp_path):
    store = spool_numpy(rng.randn(600, 4), tmp_path / "s", chunk_rows=256)
    victim = store.root / store.chunk_meta(1)["file"]
    data = victim.read_bytes()
    victim.write_bytes(data[: len(data) // 2])
    back = ChunkStore.open(tmp_path / "s")
    with pytest.raises(ChunkIntegrityError) as ei:
        back.read_chunk(1)
    msg = str(ei.value)
    assert "chunk 1" in msg
    assert f"offset {len(data) // 2}" in msg
    # chunk 0 still reads fine — corruption is isolated, not fatal-global
    back.read_chunk(0)


def test_corrupt_chunk_crc_fails_loudly(rng, tmp_path):
    store = spool_numpy(rng.randn(600, 4), tmp_path / "s", chunk_rows=256)
    victim = store.root / store.chunk_meta(2)["file"]
    raw = bytearray(victim.read_bytes())
    raw[len(raw) // 2] ^= 0xFF  # bit-flip, same size
    victim.write_bytes(bytes(raw))
    with pytest.raises(ChunkIntegrityError) as ei:
        ChunkStore.open(tmp_path / "s").read_chunk(2)
    assert "chunk 2" in str(ei.value) and "crc32" in str(ei.value)


def test_resume_discards_stragglers_and_continues(rng, tmp_path):
    """A crashed writer leaves committed chunks + a .tmp straggler and
    complete=false; resume() keeps the prefix, drops the straggler,
    and appending continues from total_rows."""
    X = rng.randn(900, 3)
    store = ChunkStore.create(tmp_path / "s", n_features=3, chunk_rows=256)
    store.append_rows(X[:600])  # commits 2 full chunks, buffers 88
    committed = store.total_rows
    assert committed == 512 and not store.complete
    # simulate the crash artifacts: an uncommitted tmp chunk
    (tmp_path / "s" / "chunk_000002.npz.tmp").write_bytes(b"partial")
    resumed = ChunkStore.resume(tmp_path / "s")
    assert resumed.total_rows == 512
    assert not list((tmp_path / "s").glob("*.tmp"))
    resumed.append_rows(X[512:])
    resumed.finalize()
    back = ChunkStore.open(tmp_path / "s")
    assert back.complete and back.total_rows == 900
    got = np.concatenate(
        [a["cols"].T for _i, _r, a in back.iter_chunks()]
    )
    np.testing.assert_array_equal(got, X)


# ------------------------------------------------------------- prefetch
def test_prefetcher_ordered_and_bounded():
    loads = []

    def load(i):
        loads.append(i)
        return np.full((2, 4), i, np.int32), {"i": i}

    pf = ChunkPrefetcher(load, n_chunks=6, depth=2, device_put=False)
    assert pf._q.maxsize == 2  # bounded queue is the contract
    seen = [(idx, info["i"]) for idx, _buf, info in pf]
    pf.close()
    assert seen == [(i, i) for i in range(6)]
    assert sorted(loads) == list(range(6))


def test_prefetcher_error_propagates():
    def load(i):
        if i == 1:
            raise ValueError("disk on fire")
        return np.zeros((1, 1), np.int32), {}

    pf = ChunkPrefetcher(load, n_chunks=3, depth=1, device_put=False)
    with pytest.raises(RuntimeError, match="prefetch reader failed"):
        list(pf)
    pf.close()


# --------------------------------------------- streamed fit: bit-exact
def test_chunked_fit_bit_exact_and_flat_rss(rng):
    X, y = _xy(rng)
    params = dict(objective="regression", num_leaves=15, verbosity=-1,
                  seed=7, deterministic=True)
    ref = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)

    reset_stats()
    p2 = dict(params, data_source="chunked", ram_budget_mb=8,
              data_chunk_rows=2048)
    got = lgb.train(p2, lgb.Dataset(X, label=y, params=p2),
                    num_boost_round=8)

    assert _strip_data_params(got.model_to_string()) == \
        _strip_data_params(ref.model_to_string())
    np.testing.assert_array_equal(got.predict(X), ref.predict(X))

    st = last_stats()
    assert st is not None
    assert {"spool", "pass1", "pass2", "assemble"} <= set(st)
    asm = st["assemble"]
    assert asm["chunks"] == 2  # 3000 rows / 2048
    assert asm["prefetch_depth"] >= 1
    # flat per-chunk host memory: steady-state RSS spread under 64 MB
    # (chunk 0 absorbs the one-time buffer + compile cost and is
    # excluded from the spread by construction)
    assert asm["rss_spread_mb"] <= 64.0
    assert all(c["rss_mb"] > 0 for c in asm["per_chunk"])


def test_chunked_manifest_lands_in_run_manifest(rng, tmp_path):
    from lightgbm_tpu.obs.manifest import build_manifest

    X, y = _xy(rng, n=1200, f=4)
    reset_stats()
    p = dict(objective="regression", verbosity=-1, data_source="chunked",
             data_chunk_rows=2048)
    lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=2)
    man = build_manifest(config=p)
    assert "data_plane" in man
    assert "assemble" in man["data_plane"]


def test_sequence_vs_chunked_bit_equal_bins(rng):
    """The Sequence streaming path and the chunked path draw the same
    pass-1 sample, so their device bin matrices must match bit for
    bit."""
    X, y = _xy(rng, n=2500, f=6)

    class Seq(lgb.Sequence):
        batch_size = 512

        def __len__(self):
            return X.shape[0]

        def __getitem__(self, idx):
            return X[idx]

    ds_seq = lgb.Dataset(Seq(), label=y).construct()
    p = dict(data_source="chunked", data_chunk_rows=2048)
    ds_chk = lgb.Dataset(X, label=y, params=p).construct()
    a = np.asarray(ds_seq._binned.device_arrays()["bins"])
    b = np.asarray(ds_chk._binned.device_arrays()["bins"])
    np.testing.assert_array_equal(a, b)


def test_streamed_subset_matches_inram(rng):
    X, y = _xy(rng, n=2000, f=5)
    p = dict(data_source="chunked", data_chunk_rows=2048)
    ds_chk = lgb.Dataset(X, label=y, params=p).construct()
    ds_ref = lgb.Dataset(X, label=y).construct()
    idx = np.sort(np.random.RandomState(3).choice(2000, 300, replace=False))
    sub_chk = ds_chk._binned.copy_subrow(idx)
    sub_ref = ds_ref._binned.copy_subrow(idx)
    np.testing.assert_array_equal(sub_chk.bins, sub_ref.bins)
    np.testing.assert_array_equal(sub_chk.metadata.label,
                                  sub_ref.metadata.label)


def test_save_binary_roundtrip_streamed(rng, tmp_path):
    from lightgbm_tpu.parsers import load_binary, save_binary

    X, y = _xy(rng, n=1500, f=4)
    p = dict(data_source="chunked", data_chunk_rows=2048)
    ds = lgb.Dataset(X, label=y, params=p).construct()
    path = str(tmp_path / "cache.bin")
    save_binary(ds._binned, path)
    back = load_binary(path)
    ref = lgb.Dataset(X, label=y).construct()
    np.testing.assert_array_equal(back.bins, ref._binned.bins)


# ----------------------------------------------------------------- dask
class _FakeDelayed:
    def __init__(self, block):
        self._block = block

    def compute(self):
        return self._block


class _FakeCollection:
    """Duck-typed Dask collection: to_delayed() partitions + compute().
    Exercises the partition-spool path without dask installed."""

    def __init__(self, X, nparts):
        self._parts = np.array_split(X, nparts)

    def to_delayed(self):
        return [_FakeDelayed(p) for p in self._parts]

    def compute(self):
        return np.concatenate(self._parts)


def test_dask_partitions_spool_through_store(rng):
    from lightgbm_tpu.dask import DaskLGBMRegressor

    X, y = _xy(rng, n=2200, f=5)
    coll = _FakeCollection(X, nparts=4)

    reset_stats()
    m_chk = DaskLGBMRegressor(
        n_estimators=5, verbosity=-1, data_source="chunked",
        data_chunk_rows=2048,
    ).fit(coll, y)
    st = last_stats()
    assert st is not None and st["spool"]["rows"] == 2200

    m_ref = DaskLGBMRegressor(n_estimators=5, verbosity=-1).fit(X, y)
    np.testing.assert_array_equal(m_chk.predict(X), m_ref.predict(X))


def test_dask_fallback_without_store(rng):
    """data_source unset: legacy single-process materialize semantics."""
    from lightgbm_tpu.dask import DaskLGBMRegressor

    X, y = _xy(rng, n=800, f=4)
    m = DaskLGBMRegressor(n_estimators=3, verbosity=-1).fit(
        _FakeCollection(X, nparts=3), y
    )
    assert m.predict(X).shape == (800,)


# --------------------------------------------------------- budget knob
def test_warn_over_budget_is_single_path(caplog):
    assert warn_over_budget("thing", 2 << 20, ram_budget_mb=1, hint="h")
    assert not warn_over_budget("thing", 2 << 20, ram_budget_mb=8, hint="h")
    # 0 = the legacy 1 GB default threshold
    assert not warn_over_budget("thing", 1 << 30, ram_budget_mb=0, hint="h")
    assert warn_over_budget("thing", (1 << 30) + 1, ram_budget_mb=0, hint="h")


def test_spooled_data_flows_through_sklearn(rng, tmp_path):
    from lightgbm_tpu.sklearn import LGBMRegressor

    X, y = _xy(rng, n=1000, f=4)
    sd = SpooledData(spool_numpy(X, tmp_path / "s", chunk_rows=2048))
    assert sd.shape == (1000, 4)
    m = LGBMRegressor(n_estimators=4, verbosity=-1,
                      data_source="chunked").fit(sd, y)
    ref = LGBMRegressor(n_estimators=4, verbosity=-1).fit(X, y)
    np.testing.assert_array_equal(m.predict(X), ref.predict(X))


# ------------------------------------------------------------ slow red
@pytest.mark.slow
def test_kill9_mid_spool_leaves_resumable_spool(tmp_path):
    """kill -9 the spooling process mid-write; the survivor spool must
    resume: committed prefix intact, stragglers discarded, appending
    continues to a complete store."""
    spool = tmp_path / "s"
    script = textwrap.dedent(f"""
        import numpy as np, sys
        from lightgbm_tpu.data.store import ChunkStore
        store = ChunkStore.create({str(spool)!r}, n_features=6,
                                  chunk_rows=4096)
        rng = np.random.RandomState(0)
        print("READY", flush=True)
        for i in range(10_000):
            store.append_rows(rng.randn(997, 6))
    """)
    proc = subprocess.Popen(
        [sys.executable, "-c", script], cwd=str(REPO),
        stdout=subprocess.PIPE, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.stdout.readline().strip() == b"READY"
    # let it commit a few chunks, then kill -9 mid-write
    import time

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            if ChunkStore.open(spool).num_chunks >= 3:
                break
        except Exception:
            pass
        time.sleep(0.05)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    resumed = ChunkStore.resume(spool)
    rows_kept = resumed.total_rows
    assert rows_kept >= 3 * 4096
    assert rows_kept % 4096 == 0  # only whole committed chunks survive
    rng = np.random.RandomState(1)
    resumed.append_rows(rng.randn(1000, 6))
    resumed.finalize()
    back = ChunkStore.open(spool)
    assert back.complete
    assert back.total_rows == rows_kept + 1000
    for i in range(back.num_chunks):
        back.read_chunk(i)  # every chunk passes size+crc


@pytest.mark.slow
def test_large_fit_exceeds_budget_flat_rss(rng):
    """Fit on data whose raw footprint exceeds ram_budget_mb; the
    assemble manifest must show flat steady-state per-chunk RSS."""
    n, f = 2_000_000, 28  # 448 MB raw float64 >> 64 MB budget
    X = rng.randn(n, f).astype(np.float64)
    y = X[:, 0] + 0.1 * rng.randn(n)
    reset_stats()
    p = dict(objective="regression", num_leaves=31, verbosity=-1,
             data_source="chunked", ram_budget_mb=64)
    lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=3)
    st = last_stats()
    raw_mb = n * f * 8 / (1 << 20)
    assert raw_mb > 64
    asm = st["assemble"]
    assert asm["chunks"] >= 4
    # steady-state spread small relative to the dataset itself
    assert asm["rss_spread_mb"] < raw_mb / 4
