"""Per-node split extras: extra_trees, feature_fraction_bynode,
interaction_constraints, CEGB penalties (reference
col_sampler.hpp / cost_effective_gradient_boosting.hpp)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=3000, f=6, seed=4):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = ((X @ w + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    return X, y


BASE = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
            verbosity=-1)


def _train(params, X, y, rounds=5):
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    return lgb.train(dict(params), ds, num_boost_round=rounds)


def _tree_paths(tree):
    """All root->leaf feature paths of a host Tree."""
    paths = []

    def walk(node, feats):
        if node < 0:
            paths.append(feats)
            return
        f = int(tree.split_feature[node])
        walk(int(tree.left_child[node]), feats | {f})
        walk(int(tree.right_child[node]), feats | {f})

    if tree.num_leaves > 1:
        walk(0, set())
    return paths


def test_extra_trees_runs_and_differs():
    X, y = _problem()
    b0 = _train(BASE, X, y)
    b1 = _train({**BASE, "extra_trees": True}, X, y)
    b2 = _train({**BASE, "extra_trees": True}, X, y)
    # deterministic given the seed, different from the exhaustive scan
    np.testing.assert_allclose(b1.predict(X), b2.predict(X))
    assert not np.allclose(b0.predict(X), b1.predict(X))
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y, b1.predict(X)) > 0.8  # still learns


def test_feature_fraction_bynode():
    X, y = _problem()
    b = _train({**BASE, "feature_fraction_bynode": 0.5}, X, y)
    assert b.num_trees() == 5
    # per-node sampling: across all trees more than bynode*F distinct
    # features appear (per-TREE sampling with fraction 0.5 could too,
    # but per-node must; smoke-level assertion)
    feats = set()
    for t in b._gbdt.models:
        for p in _tree_paths(t):
            feats |= p
    assert len(feats) >= 4


def test_interaction_constraints_respected():
    X, y = _problem(f=6)
    b = _train(
        {**BASE, "interaction_constraints": "[0,1,2],[3,4,5]"}, X, y,
        rounds=8,
    )
    groups = [set([0, 1, 2]), set([3, 4, 5])]
    for t in b._gbdt.models:
        for path in _tree_paths(t):
            assert any(path <= g for g in groups), (
                f"path {path} spans constraint groups"
            )


def test_cegb_split_penalty_shrinks_trees():
    X, y = _problem()
    b0 = _train(BASE, X, y)
    # a huge per-data split penalty makes every split unprofitable
    b1 = _train({**BASE, "cegb_tradeoff": 1.0, "cegb_penalty_split": 1e6},
                X, y)
    n0 = sum(t.num_leaves for t in b0._gbdt.models)
    n1 = sum(t.num_leaves for t in b1._gbdt.models)
    assert n1 < n0
    assert all(t.num_leaves == 1 for t in b1._gbdt.models)


def test_cegb_coupled_penalty_avoids_expensive_feature():
    rs = np.random.RandomState(8)
    X = rs.randn(3000, 3)
    # feature 0 slightly better than feature 1, feature 2 noise
    y = ((1.0 * X[:, 0] + 0.9 * X[:, 1] + 0.2 * rs.randn(3000)) > 0).astype(
        np.float64
    )
    pen = [1e6, 0.0, 0.0]
    b = _train(
        {**BASE, "cegb_tradeoff": 1.0,
         "cegb_penalty_feature_coupled": pen}, X, y, rounds=4,
    )
    for t in b._gbdt.models:
        for p in _tree_paths(t):
            assert 0 not in p, "penalized feature was used"


def test_forced_splits(tmp_path):
    """forcedsplits_filename (serial_tree_learner.cpp:627 ForceSplits):
    the tree's first splits follow the json plan exactly."""
    import json

    X, y = _problem(f=4, seed=6)
    plan = {
        "feature": 2,
        "threshold": 0.0,
        "left": {"feature": 1, "threshold": 0.5},
        "right": {"feature": 3, "threshold": -0.25},
    }
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(plan))
    b = _train(
        {**BASE, "forcedsplits_filename": str(p)}, X, y, rounds=2,
    )
    for t in b._gbdt.models:
        # split 0: root forced on feature 2 at ~0.0
        assert int(t.split_feature[0]) == 2
        assert abs(float(t.threshold[0])) < 0.2
        # split 1 = left child (leaf 0) forced on feature 1; split 2 =
        # right child (leaf 1) forced on feature 3
        assert int(t.split_feature[1]) == 1
        assert int(t.split_feature[2]) == 3
        # node 0's children are the forced internal nodes
        assert int(t.left_child[0]) == 1
        assert int(t.right_child[0]) == 2


def test_forced_splits_invalid_file_warns(tmp_path, capsys):
    X, y = _problem(seed=7)
    p = tmp_path / "nope.json"
    b = _train({**BASE, "forcedsplits_filename": str(p), "verbosity": 0},
               X, y, rounds=1)
    assert b.num_trees() == 1  # training proceeds without forcing


def test_forced_splits_with_feature_learner(tmp_path):
    """tree_learner=feature + forcedsplits must not crash (ADVICE r3):
    the plan is dropped with a warning, training proceeds."""
    import json

    X, y = _problem(f=4, seed=6)
    plan = {"feature": 2, "threshold": 0.0}
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(plan))
    b = _train(
        {**BASE, "forcedsplits_filename": str(p),
         "tree_learner": "feature"}, X, y, rounds=2,
    )
    assert b.num_trees() == 2
