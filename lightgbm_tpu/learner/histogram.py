"""Feature-histogram construction as MXU matmuls.

The reference builds per-(leaf, feature) histograms of (sum_grad,
sum_hess, count) with sequential scatter loops on CPU
(src/io/dense_bin.hpp:99-174 ConstructHistogram) and shared-memory
atomics on CUDA (src/treelearner/cuda/cuda_histogram_constructor.cu).
Scatter-add is the wrong primitive for a TPU; instead each block of rows
is expanded to a one-hot {0,1} matrix over the bin axis and contracted
against the (grad, hess, count) channels — a batched matmul that tiles
onto the MXU. A `lax.scan` over row blocks bounds the one-hot
materialization to one block at a time.

Accumulation is float32 (`preferred_element_type`), matching the CUDA
backend's float histograms (gpu_hist_t) rather than the CPU's doubles.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def leaf_histogram(
    bins_blocked: jax.Array,  # (nblocks, F, Bk) int32 — feature-major row blocks
    gh: jax.Array,  # (N, 3) float32 — (grad, hess, count) already masked to the leaf
    num_bins: int,  # uniform bin-axis size B
) -> jax.Array:
    """Return (F, B, 3) histogram of the rows whose gh mask is nonzero."""
    nblocks, F, Bk = bins_blocked.shape
    gh_blocked = gh.reshape(nblocks, Bk, 3)

    iota = jnp.arange(num_bins, dtype=bins_blocked.dtype)

    def body(acc, xs):
        b, g = xs  # (F, Bk) int, (Bk, 3) f32
        onehot = (b[:, :, None] == iota).astype(jnp.float32)  # (F, Bk, B)
        acc = acc + jnp.einsum(
            "frb,rc->fbc", onehot, g, preferred_element_type=jnp.float32
        )
        return acc, None

    init = jnp.zeros((F, num_bins, 3), dtype=jnp.float32)
    hist, _ = lax.scan(body, init, (bins_blocked, gh_blocked))
    return hist


def masked_leaf_histogram(
    bins_blocked: jax.Array,
    gh_all: jax.Array,  # (N, 3) masked for validity/bagging but not leaf
    row_leaf: jax.Array,  # (N,) int32
    leaf: jax.Array,  # scalar int32
    num_bins: int,
) -> jax.Array:
    """Histogram of rows currently assigned to `leaf`."""
    mask = (row_leaf == leaf).astype(gh_all.dtype)
    return leaf_histogram(bins_blocked, gh_all * mask[:, None], num_bins)


def root_sums(gh: jax.Array, axis_name: Optional[str] = None) -> jax.Array:
    """(sum_grad, sum_hess, count) over all in-bag rows; float64-free but
    accumulated in f32 pairwise by jnp.sum. Globally reduced over the data
    mesh axis when present (reference data_parallel_tree_learner.cpp:169-221
    root allreduce)."""
    s = jnp.sum(gh, axis=0)
    if axis_name is not None:
        s = lax.psum(s, axis_name)
    return s
