"""Pallas TPU histogram-construction kernel.

The histogram is the reference's single hottest loop
(src/io/dense_bin.hpp:99-174 ConstructHistogram on CPU, shared-memory
atomics in src/treelearner/cuda/cuda_histogram_constructor.cu on CUDA).
A TPU has no vector scatter, so the kernel reformulates scatter-add as
a one-hot contraction — but unlike a plain XLA einsum, the one-hot
matrix only ever exists one (HIST_BLK, B) tile at a time in VMEM,
never in HBM. Per grid step (one row block):

    bins tile (F, blk) int32, gh tile (8, blk) f32    -> VMEM
    bt = transpose(bins tile)                          (blk, F), one relayout
    for each feature f (static unroll):
        onehot = (bt[:, f:f+1] == iota_B)              (blk, B) bf16
        acc[:, f*B:(f+1)*B] += gh @ onehot             MXU (8,blk)@(blk,B)
    last step: out = acc

Inputs are feature-major (rows on the LANE axis) because TPU memory
tiles pad the minor-most dim to 128 lanes — a row-major (N, 28) matrix
would physically occupy 4.5x its size in HBM. One in-kernel transpose
per tile puts rows on sublanes for the one-hot compare. The channel
axis is padded 3 -> 8 (bf16x2-split grad/hess + count, see
histogram.build_gh8) to match the f32 sublane tile; f32 accumulation
into a (8, F*B) VMEM scratch across grid steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import CH, HIST_BLK


def _hist_kernel(bins_ref, gh_ref, out_ref, acc_ref, *, F: int, B: int, blk: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    bt = jnp.transpose(bins_ref[...])  # (blk, F) int32
    g = gh_ref[...].astype(jnp.bfloat16)  # (CH, blk)
    iota = lax.broadcasted_iota(jnp.int32, (blk, B), 1)
    for f in range(F):
        onehot = (bt[:, f : f + 1] == iota).astype(jnp.bfloat16)  # (blk, B)
        acc_ref[:, f * B : (f + 1) * B] += jnp.dot(
            g, onehot, preferred_element_type=jnp.float32
        )

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        out_ref[...] = acc_ref[...]


@functools.partial(jax.jit, static_argnames=("num_bins", "blk"))
def hist_tpu(
    bins_fm: jax.Array, gh8: jax.Array, num_bins: int, blk: int = HIST_BLK
) -> jax.Array:
    """(F, N) int32 bins + (CH, N) f32 channels -> (CH, F, B) f32.

    N must be a multiple of blk; callers pad rows with gh == 0.
    """
    F, N = bins_fm.shape
    assert N % blk == 0, (N, blk)
    assert gh8.shape == (CH, N), gh8.shape
    B = num_bins
    nb = N // blk

    out = pl.pallas_call(
        functools.partial(_hist_kernel, F=F, B=B, blk=blk),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((CH, F * B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((CH, F * B), jnp.float32),
        scratch_shapes=[pltpu.VMEM((CH, F * B), jnp.float32)],
    )(bins_fm, gh8)
    return out.reshape(CH, F, B)
