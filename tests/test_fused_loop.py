"""Fused device loop (one dispatch per iteration, chunked eval fetch)
must be bit-for-bit equivalent in behavior to the synchronous path."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.boosting as bmod
import lightgbm_tpu.callback as cbm


def _train_both(params, X, y, Xv, yv, rounds, callbacks_factory=lambda r: [cbm.record_evaluation(r)]):
    res_f, res_s = {}, {}
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    dv = lgb.Dataset(Xv, label=yv, free_raw_data=False)
    bst_f = lgb.train(dict(params), ds, num_boost_round=rounds,
                      valid_sets=[dv], valid_names=["va"],
                      callbacks=callbacks_factory(res_f))
    orig = bmod.GBDT.fused_eligible
    bmod.GBDT.fused_eligible = lambda self: False
    try:
        ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
        dv2 = lgb.Dataset(Xv, label=yv, free_raw_data=False)
        bst_s = lgb.train(dict(params), ds2, num_boost_round=rounds,
                          valid_sets=[dv2], valid_names=["va"],
                          callbacks=callbacks_factory(res_s))
    finally:
        bmod.GBDT.fused_eligible = orig
    return bst_f, bst_s, res_f, res_s


def test_fused_equals_sync_binary():
    rs = np.random.RandomState(3)
    X = rs.randn(1200, 6)
    w = rs.randn(6)
    y = ((X @ w + 0.3 * rs.randn(1200)) > 0).astype(float)
    bst_f, bst_s, res_f, res_s = _train_both(
        {"objective": "binary", "num_leaves": 7,
         "metric": ["auc", "binary_logloss"], "verbosity": -1},
        X[:800], y[:800], X[800:], y[800:], 15,
    )
    np.testing.assert_allclose(
        bst_f.predict(X[800:]), bst_s.predict(X[800:]), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(res_f["va"]["auc"], res_s["va"]["auc"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        res_f["va"]["binary_logloss"], res_s["va"]["binary_logloss"],
        rtol=1e-4, atol=1e-6,
    )


def test_fused_early_stopping_matches_reference_timing():
    rs = np.random.RandomState(5)
    X = rs.randn(900, 5)
    y = (X[:, 0] + 0.5 * rs.randn(900) > 0).astype(float)
    ds = lgb.Dataset(X[:600], label=y[:600], free_raw_data=False)
    dv = lgb.Dataset(X[600:], label=y[600:], free_raw_data=False)
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "metric": "auc",
         "verbosity": -1, "early_stopping_round": 3},
        ds, num_boost_round=300, valid_sets=[dv],
        callbacks=[cbm.record_evaluation(res)],
    )
    # reference semantics: training stops exactly early_stopping_round
    # iterations after the best one; trained-ahead chunk iters truncated
    assert bst.best_iteration >= 1
    assert bst.num_trees() == bst.best_iteration + 3


def test_fused_nonzero_mean_regression_bias():
    rs = np.random.RandomState(11)
    X = rs.randn(1000, 5)
    y = 25.0 + X[:, 0] + 0.1 * rs.randn(1000)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "learning_rate": 0.2,
         "metric": "l2", "verbosity": -1},
        ds, num_boost_round=30,
    )
    pred = bst.predict(X)
    assert float(np.sqrt(np.mean((pred - y) ** 2))) < 0.5


def test_fused_bagging_and_feature_fraction():
    rs = np.random.RandomState(13)
    X = rs.randn(1500, 8)
    w = rs.randn(8)
    y = ((X @ w) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "metric": "auc",
         "bagging_fraction": 0.6, "bagging_freq": 2,
         "feature_fraction": 0.7, "verbosity": -1},
        ds, num_boost_round=25, valid_sets=[ds], valid_names=["tr"],
        callbacks=[cbm.record_evaluation(res)],
    )
    assert res["tr"]["auc"][-1] > 0.9


def test_fused_step_memo_across_boosters():
    """cv folds / repeated trains with identical shapes+config reuse one
    traced+compiled fused step (VERDICT r4 item 6): the second Booster
    must skip trace+compile entirely."""
    import time

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting import _FUSED_STEP_CACHE

    rs = np.random.RandomState(0)
    n, f = 4096, 6
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "metric": "auc", "min_data_in_leaf": 5}

    def one(seed):
        X = rs.randn(n, f)
        w = rs.randn(f)
        y = ((X @ w + 0.3 * rs.randn(n)) > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        vs = lgb.Dataset(X[:1024].copy(), label=y[:1024].copy(),
                         reference=ds, free_raw_data=False)
        t0 = time.time()
        bst = lgb.train(dict(params), ds, num_boost_round=8,
                        valid_sets=[vs], valid_names=["v"])
        return time.time() - t0, bst

    _FUSED_STEP_CACHE.clear()
    t1, b1 = one(1)
    assert len(_FUSED_STEP_CACHE) == 1  # step was built and memoized
    t2, b2 = one(2)
    assert len(_FUSED_STEP_CACHE) == 1  # second Booster reused it
    # the reuse must actually skip trace+compile: fold 2 pays only the
    # run itself (fold 1 includes a multi-second trace+compile even
    # with a warm persistent cache)
    assert t2 < max(t1 * 0.6, 5.0), (t1, t2)
    # both trained sane models
    p1, p2 = b1.predict(rs.randn(50, f)), b2.predict(rs.randn(50, f))
    assert np.isfinite(p1).all() and np.isfinite(p2).all()


def test_fused_step_memo_excludes_ranking():
    """Ranking groups bake fold data into the trace — those configs
    must NOT share the memoized step."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting import _FUSED_STEP_CACHE

    rs = np.random.RandomState(3)
    n, f = 2048, 5
    X = rs.randn(n, f)
    y = rs.randint(0, 4, n).astype(np.float64)
    group = np.full(n // 16, 16, np.int64)
    _FUSED_STEP_CACHE.clear()
    ds = lgb.Dataset(X, label=y, group=group, free_raw_data=False)
    lgb.train({"objective": "lambdarank", "num_leaves": 15,
               "verbosity": -1, "metric": "ndcg", "eval_at": [3]},
              ds, num_boost_round=3, valid_sets=[ds], valid_names=["t"])
    assert len(_FUSED_STEP_CACHE) == 0
