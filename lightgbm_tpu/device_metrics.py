"""Device-resident metric evaluation for the fused training loop.

The reference evaluates metrics on host every iteration
(GBDT::EvalAndCheckEarlyStopping, gbdt.cpp:482). On this TPU runtime a
single device->host readback costs ~100ms, so per-iteration host eval
destroys throughput (VERDICT round 1, weak #8). Instead each metric gets
a traced evaluator closed over padded device label/weight arrays; the
fused iteration computes all metric values into one small (m,) f32
vector per iteration, and the engine fetches a whole chunk of them in a
single device_get.

Semantics mirror lightgbm_tpu.metrics (reference src/metric/*.hpp):
weighted means over valid (non-padding) rows, raw-score transforms per
metric, exact tie-handled AUC via one device sort.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from .config import Config


def _weights(meta_weight, valid):
    """Effective per-row weights: user weights (or 1) zeroed on padding."""
    import jax.numpy as jnp

    if meta_weight is None:
        return valid
    return meta_weight * valid


def _wmean(vals, w):
    import jax.numpy as jnp

    return jnp.sum(vals * w) / jnp.sum(w)


def _sigmoid(x, s):
    import jax.numpy as jnp

    return 1.0 / (1.0 + jnp.exp(-s * x))


def _make_pointwise(name: str, cfg: Config, label, w):
    """Returns fn(score_1d) -> scalar for pointwise metrics, or None."""
    import jax.numpy as jnp

    eps = 1e-15
    if name == "l2":
        return lambda s: _wmean((s - label) ** 2, w)
    if name == "rmse":
        return lambda s: jnp.sqrt(_wmean((s - label) ** 2, w))
    if name == "l1":
        return lambda s: _wmean(jnp.abs(s - label), w)
    if name == "r2":

        def _r2(s):
            ybar = _wmean(label, w)
            ss_res = jnp.sum(w * (label - s) ** 2)
            ss_tot = jnp.sum(w * (label - ybar) ** 2)
            return jnp.where(ss_tot > 0, 1.0 - ss_res / ss_tot, 0.0)

        return _r2
    if name == "quantile":
        a = cfg.alpha

        def _q(s):
            d = label - s
            return _wmean(jnp.where(d >= 0, a * d, (a - 1.0) * d), w)

        return _q
    if name == "huber":
        a = cfg.alpha

        def _h(s):
            d = jnp.abs(s - label)
            return _wmean(
                jnp.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a)), w
            )

        return _h
    if name == "fair":
        c = cfg.fair_c

        def _f(s):
            x = jnp.abs(s - label)
            return _wmean(c * x - c * c * jnp.log1p(x / c), w)

        return _f
    if name == "poisson":

        def _p(s):
            # score is the raw (log) margin, prediction = exp(score)
            return _wmean(jnp.exp(s) - label * s, w)

        return _p
    if name == "mape":
        return lambda s: _wmean(
            jnp.abs((label - s) / jnp.maximum(1.0, jnp.abs(label))), w
        )
    if name == "gamma":

        def _g(s):
            p = jnp.exp(s)
            return _wmean(
                label / p + s - 1.0
                - jnp.where(label > 0, jnp.log(jnp.maximum(label, eps)), 0.0),
                w,
            )

        return _g
    if name == "gamma_deviance":

        def _gd(s):
            p = jnp.exp(s)
            r = label / jnp.maximum(p, eps)
            return 2.0 * _wmean(r - jnp.log(jnp.maximum(r, eps)) - 1.0, w)

        return _gd
    if name == "tweedie":
        rho = cfg.tweedie_variance_power

        def _t(s):
            p = jnp.exp(s)
            a = label * jnp.exp((1.0 - rho) * s) / (1.0 - rho)
            b = jnp.exp((2.0 - rho) * s) / (2.0 - rho)
            return _wmean(-a + b, w)

        return _t
    if name in ("binary_logloss",):
        sg = cfg.sigmoid

        def _bl(s):
            p = jnp.clip(_sigmoid(s, sg), eps, 1.0 - eps)
            return _wmean(
                -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p)), w
            )

        return _bl
    if name == "binary_error":
        sg = cfg.sigmoid

        def _be(s):
            p = _sigmoid(s, sg)
            return _wmean(
                ((p > 0.5) != (label > 0.5)).astype(jnp.float32), w
            )

        return _be
    if name in ("cross_entropy", "xentropy"):
        sg = 1.0

        def _xe(s):
            p = jnp.clip(_sigmoid(s, sg), eps, 1.0 - eps)
            return _wmean(
                -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p)), w
            )

        return _xe
    return None


def _make_auc(label, w):
    """Exact weighted AUC with tie handling via one device sort
    (reference src/metric/binary_metric.hpp AUCMetric). Sorts
    (score, posw, negw) ascending and accumulates per-tie-group
    gp*(cum_neg_before + 0.5*gn) fully vectorized."""
    import jax.numpy as jnp
    from jax import lax

    posw = w * (label > 0)
    negw = w * (label <= 0)

    def _auc(s):
        # padding rows have w == 0 so their position is irrelevant
        sk, pw, nw = lax.sort((s, posw, negw), num_keys=1)
        cn = jnp.cumsum(nw)  # inclusive neg-weight prefix
        cp = jnp.cumsum(pw)
        # tie-group boundaries on the sorted scores
        start = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
        # forward-fill the group-start exclusive prefix: cn_excl is
        # non-decreasing, so a cummax over masked starts is a fill
        cn_excl = cn - nw
        cp_excl = cp - pw
        gstart_cn = lax.associative_scan(jnp.maximum, jnp.where(start, cn_excl, -1.0))
        gstart_cp = lax.associative_scan(jnp.maximum, jnp.where(start, cp_excl, -1.0))
        # per-element group-neg total: group end value - group start value;
        # group end via reverse fill of (next-start -> inclusive value)
        end = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones(1, bool)])
        gend_cn = lax.associative_scan(
            jnp.minimum, jnp.where(end, cn, jnp.inf), reverse=True
        )
        gn = gend_cn - gstart_cn
        # each positive contributes w * (neg strictly below + 0.5 * ties)
        auc_sum = jnp.sum(pw * (gstart_cn + 0.5 * gn))
        tot_p = cp[-1]
        tot_n = cn[-1]
        ok = (tot_p > 0) & (tot_n > 0)
        return jnp.where(ok, auc_sum / jnp.maximum(tot_p * tot_n, 1e-30), 1.0)

    return _auc


def _make_multiclass(name: str, cfg: Config, label, w, num_class: int):
    import jax
    import jax.numpy as jnp

    eps = 1e-15
    lab_i = label.astype(jnp.int32)

    if name in ("multi_logloss",):

        def _ml(score):  # (K, N)
            lse = jax.nn.logsumexp(score, axis=0)
            picked = jnp.take_along_axis(score, lab_i[None, :], axis=0)[0]
            return _wmean(lse - picked, w)

        return _ml
    if name == "multi_error":
        k_top = cfg.multi_error_top_k

        def _me(score):
            if k_top <= 1:
                pred = jnp.argmax(score, axis=0)
                return _wmean((pred != lab_i).astype(jnp.float32), w)
            true_s = jnp.take_along_axis(score, lab_i[None, :], axis=0)[0]
            rank = jnp.sum(score > true_s[None, :], axis=0)
            return _wmean((rank >= k_top).astype(jnp.float32), w)

        return _me
    return None


class DeviceEvalSet:
    """All metrics of one dataset as a single traced fn(score)->(m,) f32."""

    def __init__(
        self,
        cfg: Config,
        metric_names: List[str],
        higher_better: List[bool],
        label,
        weight,
        valid,
        num_class: int,
        group=None,
    ):
        import jax.numpy as jnp

        self.names = metric_names
        self.higher_better = higher_better
        w = _weights(weight, valid)
        fns = []
        ndcg_factory = None
        map_factory = None
        for nm in metric_names:
            base = nm.split("@")[0]  # display names may carry "@k"
            if base == "ndcg":
                if ndcg_factory is None:
                    ndcg_factory = _make_ndcg_factory(cfg, label, group)
                fns.append((ndcg_factory(int(nm.split("@")[1])), False))
                continue
            if base == "map":
                if map_factory is None:
                    map_factory = _make_map_factory(cfg, label, group)
                fns.append((map_factory(int(nm.split("@")[1])), False))
                continue
            if num_class > 1 and base in ("multi_logloss", "multi_error"):
                fns.append((_make_multiclass(base, cfg, label, w, num_class), True))
                continue
            if base == "auc":
                fns.append((_make_auc(label, w), False))
                continue
            f = _make_pointwise(base, cfg, label, w)
            if f is not None:
                fns.append((f, False))
                continue
            hf = _make_host_fallback(
                nm, cfg, label, weight, valid, num_class, group=group
            )
            if hf is None:
                raise NotImplementedError(nm)
            fns.append((hf, True))  # gets the full (K, N) score
        self._fns = fns

    def __call__(self, score):
        """score (K, Np); returns (m,) f32."""
        import jax.numpy as jnp

        vals = []
        for f, is_multi in self._fns:
            vals.append(f(score) if is_multi else f(score[0]))
        return jnp.stack(vals) if vals else jnp.zeros(0, jnp.float32)


def _make_ndcg_factory(cfg: Config, label, group):
    """Shared (Q, M) layout for all ndcg@k fns of one dataset; the per-k
    sorts trace into the same step, so XLA CSEs them."""
    import jax.numpy as jnp

    from .learner.ranking import (
        build_query_layout,
        check_label_range,
        default_label_gain,
        ndcg_at,
    )

    npad = int(label.shape[0])
    layout = build_query_layout(np.asarray(group), npad)
    gains = list(cfg.label_gain)
    if not gains:
        gains = list(default_label_gain(int(np.asarray(label).max())))
    check_label_range(np.asarray(label), len(gains))
    gain_dev = jnp.asarray(np.asarray(gains), jnp.float32)
    label_dev = jnp.asarray(label, jnp.float32)

    def factory(k: int):
        def f(s):
            return ndcg_at(layout, s, label_dev, gain_dev, [k])[0]

        return f

    return factory


_warned_host_fallback: set = set()


def _make_host_fallback(nm: str, cfg: Config, label, weight, valid,
                        num_class: int, group=None):
    """Last-resort evaluator for a VALID metric string with no device
    implementation (VERDICT r5 weak #6): compute it on host via
    metrics.py inside a `jax.pure_callback`, so the traced eval vector
    keeps its shape and a drift between `supported_names` and the
    device implementations degrades to a warning instead of crashing.

    Warned once per metric name: the callback reintroduces the
    per-iteration device->host sync the device metrics exist to avoid
    (~100 ms on the axon runtime) — it is a correctness net, not a
    fast path. Returns None only when metrics.py does not know the
    name either (a genuinely invalid string)."""
    from . import log
    from . import metrics as host_metrics

    base = nm.split("@")[0]
    cls = host_metrics._METRICS.get(base)
    if cls is None:
        return None
    import jax
    import jax.numpy as jnp

    m = cls(cfg)
    # label/weight/valid may be TRACERS (the memoized fused step
    # constructs DeviceEvalSet inside the trace with fold arrays as jit
    # arguments) — so they ride the callback as OPERANDS; all host-side
    # masking/init happens inside the callback body on concrete values
    group_h = None if group is None else np.asarray(group)
    has_w = weight is not None
    if nm not in _warned_host_fallback:
        _warned_host_fallback.add(nm)
        log.warning(
            f"metric {nm!r} has no device implementation; computing it "
            "on host each eval via a callback (one device->host sync "
            "per iteration — expect slower fused-loop throughput)"
        )

    def _host(score, lab, wt, val) -> np.float32:
        mask = np.asarray(val) > 0
        m.init(
            np.asarray(lab)[mask],
            np.asarray(wt)[mask] if has_w else None,
            group_h,
        )
        s = np.asarray(score, np.float64)[:, mask]
        res = m.eval(s if num_class > 1 else s[0])
        return np.float32(res[0][1])

    w_arg = weight if has_w else valid  # placeholder operand when unweighted

    def f(score):
        return jax.pure_callback(
            _host, jax.ShapeDtypeStruct((), jnp.float32),
            score, label, w_arg, valid,
        )

    return f


def _make_map_factory(cfg: Config, label, group):
    """Device MAP@k (map_metric.hpp) over the shared (Q, M) layout —
    keeps metric=map ranking configs on the fused device loop."""
    import jax.numpy as jnp

    from .learner.ranking import build_query_layout, map_at

    npad = int(label.shape[0])
    layout = build_query_layout(np.asarray(group), npad)
    label_dev = jnp.asarray(label, jnp.float32)

    def factory(k: int):
        def f(s):
            return map_at(layout, s, label_dev, [k])[0]

        return f

    return factory


# metric names the device path supports (superset check happens at build)
def supported_names(metric_objs) -> Optional[Tuple[List[str], List[bool]]]:
    """Map host Metric objects -> (display names, higher_better) if all
    are device-implementable, else None. Multi-valued metrics (ndcg@k
    per eval_at entry) expand to one display name per value, matching
    the host metric's eval() tuples."""
    names, hb = [], []
    _ok = {
        "l2", "rmse", "l1", "r2", "quantile", "huber", "fair", "poisson",
        "mape", "gamma", "gamma_deviance", "tweedie", "binary_logloss",
        "binary_error", "cross_entropy", "auc", "multi_logloss",
        "multi_error", "ndcg", "map",
    }
    for m in metric_objs:
        if m.name not in _ok:
            return None
        if m.name in ("ndcg", "map"):
            if getattr(m, "group", None) is None:
                return None
            ks = list(m.config.eval_at) or [1, 2, 3, 4, 5]
            for k in ks:
                names.append(f"{m.name}@{k}")
                hb.append(True)
            continue
        display = m.name
        if m.name == "multi_error":
            k = getattr(m.config, "multi_error_top_k", 1)
            if k > 1:
                display = f"multi_error@{k}"  # match host MultiErrorMetric
        names.append(display)
        hb.append(m.higher_better)
    return names, hb
