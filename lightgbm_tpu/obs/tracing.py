"""Span tracing: Chrome trace-event export layered on the phase timer.

``timer.Timer.scope`` already wraps every instrumented host region in
``jax.named_scope``, so device profiles collected with ``jax.profiler``
carry the same names. This module adds the HOST half: while a
``TraceRecorder`` is active, every scope also records a complete-event
span (phase ``X``), and ad-hoc regions can use :func:`span` directly.
The result exports two ways:

- ``write_chrome(path)`` — Chrome trace-event JSON (open in Perfetto /
  chrome://tracing, or drop next to a ``jax.profiler`` trace captured
  over the same run via the ``profile_dir`` CLI param);
- ``write_jsonl(path)`` — one event per line for ad-hoc analysis.

Recording is host-side only (the recorder is a Python list behind a
lock); nothing here runs inside jit, so the audited jaxprs stay
callback-free — re-audited by tests/test_obs.py.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from .. import timer as _timer


class TraceRecorder:
    """Accumulates trace events; thread-safe."""

    def __init__(self, process_name: str = "lightgbm-tpu"):
        self.process_name = process_name
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self.t0 = time.perf_counter()

    # ------------------------------------------------------------------
    def add_complete(self, name: str, start_s: float, dur_s: float,
                     args: Optional[Dict[str, Any]] = None) -> None:
        """One finished span; start_s is a time.perf_counter() value."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": round((start_s - self.t0) * 1e6, 3),
            "dur": round(dur_s * 1e6, 3),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_instant(self, name: str,
                    args: Optional[Dict[str, Any]] = None) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": round((time.perf_counter() - self.t0) * 1e6, 3),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_counter(self, name: str, values: Dict[str, float]) -> None:
        ev = {
            "name": name,
            "ph": "C",
            "ts": round((time.perf_counter() - self.t0) * 1e6, 3),
            "pid": os.getpid(),
            "args": {k: float(v) for k, v in values.items()},
        }
        with self._lock:
            self._events.append(ev)

    # ------------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        meta = [{
            "name": "process_name",
            "ph": "M",
            "pid": os.getpid(),
            "args": {"name": self.process_name},
        }]
        return {
            "traceEvents": meta + self.events(),
            "displayTimeUnit": "ms",
        }

    def write_chrome(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)

    def write_jsonl(self, path: str) -> None:
        events = self.events()
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")


_lock = threading.Lock()
_active: Optional[TraceRecorder] = None


def active() -> Optional[TraceRecorder]:
    return _active


def start_tracing(process_name: str = "lightgbm-tpu") -> TraceRecorder:
    """Install a recorder as the timer's trace sink; nested starts
    return the already-active recorder (one recorder per process)."""
    global _active
    with _lock:
        if _active is not None:
            return _active
        rec = TraceRecorder(process_name)
        _active = rec
    _timer.set_trace_sink(rec.add_complete)
    return rec


def stop_tracing() -> Optional[TraceRecorder]:
    """Uninstall and return the active recorder (None if none)."""
    global _active
    with _lock:
        rec = _active
        _active = None
    _timer.set_trace_sink(None)
    return rec


@contextmanager
def tracing(chrome_path: Optional[str] = None,
            jsonl_path: Optional[str] = None) -> Iterator[TraceRecorder]:
    """Record spans for the duration of the block; optionally export on
    exit. Owns start/stop, so it must not wrap a region that already
    has an active recorder (start_tracing would alias it)."""
    rec = start_tracing()
    try:
        yield rec
    finally:
        stop_tracing()
        if chrome_path:
            rec.write_chrome(chrome_path)
        if jsonl_path:
            rec.write_jsonl(jsonl_path)


@contextmanager
def span(name: str, **args: Any) -> Iterator[None]:
    """Ad-hoc host span: records into the active recorder (no-op when
    tracing is off) and accumulates in the phase timer when enabled —
    the same dual path timer scopes take."""
    with _timer.global_timer.scope(name):
        yield
    if args:
        rec = _active
        if rec is not None:
            rec.add_instant(name, args)
