"""Persistent XLA compilation cache, enabled by default on TPU.

A cold compile of the fused training step costs ~40 s on a v5e chip
(BENCH_NOTES r4); the reference's C++ has no such cost, so out of the
box we cache compiled executables across processes the way the bench
harness does. Opt out with LGBM_TPU_NO_COMPILE_CACHE=1 or override the
location with JAX_COMPILATION_CACHE_DIR.
"""

from __future__ import annotations

import os

_done = False


def machine_tag() -> str:
    """Host fingerprint for persistent-cache directories. XLA:CPU AOT
    entries embed machine features that the cache KEY omits, so an
    entry written on a different host (the bench/test driver moves
    between machines) loads here and dies with SIGILL/SIGSEGV after
    warning "Target machine feature ... is not supported on the host
    machine" — fingerprinted directories make that impossible."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 says "flags", ARM says "Features" — either is the
                # ISA-extension list that decides AOT compatibility
                if line.lower().startswith(("flags", "features")):
                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform

    return platform.machine() or "generic"


def ensure_compile_cache() -> None:
    """Idempotent; call before the first jit dispatch. No-op when the
    user configured a cache themselves, opted out, or jax isn't on an
    accelerator (CPU compiles are cheap and tests churn trees)."""
    global _done
    if _done:
        return
    _done = True
    if os.environ.get("LGBM_TPU_NO_COMPILE_CACHE", "").lower() in (
        "1", "true", "yes",
    ):
        return
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # user-configured; jax already read it
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return
        if jax.devices()[0].platform not in ("tpu",):
            return
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "lightgbm_tpu", "jax_cache"
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        if not os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0
            )
        from . import log

        log.info(
            f"Persistent XLA compile cache enabled at {cache_dir} "
            "(LGBM_TPU_NO_COMPILE_CACHE=1 to disable)"
        )
    except Exception:  # noqa: BLE001 — never block training on cache setup
        pass
