"""Serving benchmark: QPS + latency percentiles for the scoring path.

Prints ONE JSON line and writes it to BENCH_SERVE_rNN.json next to the
training BENCH files, so serving performance is tracked
round-over-round exactly like training throughput (ROADMAP item 4; the
artifact always carries "qps", "p50_ms", "p99_ms").

What it measures: a model is trained in-process on synthetic data,
loaded into the serving ModelRegistry (bucket-padded dispatcher,
warmed), then T threads fire R score requests of B rows each through
``registry.predict`` — the same entry point both serving transports
call — and per-request wall latencies are recorded. The registry's own
LatencyStats ring (what ``/metrics`` and the stats op report) rides
along in "stats" so the benchmark's numbers and the observability
numbers can be cross-checked.

Env overrides: BENCH_SERVE_TRAIN_ROWS, BENCH_SERVE_FEATURES,
BENCH_SERVE_TREES, BENCH_SERVE_LEAVES, BENCH_SERVE_REQUESTS,
BENCH_SERVE_BATCH, BENCH_SERVE_THREADS, BENCH_SERVE_QUEUE (also drive
the microbatch-coalescing path), BENCH_SERVE_OUT (explicit output
path), BENCH_SERVE_DIR (output directory, default: repo root).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
SCHEMA = "lightgbm-tpu/bench-serve/v1"

# last builder-verified ON-CHIP serving measurement — the same
# carry-forward semantics bench.py uses for training throughput: when
# a run lands off-chip, this rides along marked `stale: true` so the
# bench gate (analysis/bench_gate.py) never reads a carried number as
# fresh. None until the first chip serving run lands; update it there
# and re-pin with `python -m lightgbm_tpu.analysis --refresh-budgets`.
LAST_TPU_VERIFIED = None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _pct(sorted_vals, p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(p * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def run_bench() -> dict:
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry

    train_rows = _env_int("BENCH_SERVE_TRAIN_ROWS", 20000)
    n_feat = _env_int("BENCH_SERVE_FEATURES", 16)
    n_trees = _env_int("BENCH_SERVE_TREES", 50)
    n_leaves = _env_int("BENCH_SERVE_LEAVES", 31)
    n_requests = _env_int("BENCH_SERVE_REQUESTS", 200)
    batch = _env_int("BENCH_SERVE_BATCH", 64)
    n_threads = _env_int("BENCH_SERVE_THREADS", 4)
    use_queue = _env_int("BENCH_SERVE_QUEUE", 0) != 0

    rs = np.random.RandomState(0)
    X = rs.randn(train_rows, n_feat).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    t0 = time.perf_counter()
    bst = lgb.train(
        {"objective": "binary", "num_leaves": n_leaves, "verbosity": -1},
        ds, num_boost_round=n_trees,
    )
    train_s = time.perf_counter() - t0

    registry = ModelRegistry(warmup=True)
    registry.load("bench", bst, num_features=n_feat)

    req = rs.randn(batch, n_feat).astype(np.float32)
    # warmup outside the timed window (compiles + first-dispatch costs)
    for _ in range(3):
        registry.predict("bench", req, via_queue=use_queue)

    latencies: list = []
    lock = threading.Lock()
    per_thread = max(n_requests // n_threads, 1)

    def worker(seed: int) -> None:
        wrs = np.random.RandomState(seed)
        mine = []
        for _ in range(per_thread):
            rows = wrs.randn(batch, n_feat).astype(np.float32)
            t = time.perf_counter()
            registry.predict("bench", rows, via_queue=use_queue)
            mine.append(time.perf_counter() - t)
        with lock:
            latencies.extend(mine)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    done = len(latencies)
    lat = sorted(latencies)
    result = {
        "schema": SCHEMA,
        "metric": "serve_score_qps",
        "qps": round(done / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(1e3 * _pct(lat, 0.50), 4),
        "p95_ms": round(1e3 * _pct(lat, 0.95), 4),
        "p99_ms": round(1e3 * _pct(lat, 0.99), 4),
        "mean_ms": round(1e3 * sum(lat) / len(lat), 4) if lat else 0.0,
        "rows_per_sec": round(done * batch / wall, 1) if wall > 0 else 0.0,
        "requests": done,
        "batch_rows": batch,
        "threads": n_threads,
        "via_queue": use_queue,
        "wall_s": round(wall, 3),
        "model": {"trees": n_trees, "leaves": n_leaves,
                  "features": n_feat, "train_rows": train_rows,
                  "train_s": round(train_s, 2)},
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        # the observability view of the same run (LatencyStats ring —
        # what /metrics and the stats op report)
        "stats": registry.stats().get("bench", {}),
        "created_unix": time.time(),
        "run_id": f"{int(time.time())}-{os.getpid()}",
    }
    if LAST_TPU_VERIFIED:
        # same staleness rule as bench.py: carried chip numbers are
        # fresh only when THIS run actually executed on the chip
        result["last_tpu_verified"] = dict(
            LAST_TPU_VERIFIED, stale=result["platform"] != "tpu"
        )
    return result


def _next_out_path() -> str:
    if os.environ.get("BENCH_SERVE_OUT"):
        return os.environ["BENCH_SERVE_OUT"]
    out_dir = os.environ.get("BENCH_SERVE_DIR", REPO)
    rounds = [0]
    for p in glob.glob(os.path.join(out_dir, "BENCH_SERVE_r*.json")):
        m = re.search(r"BENCH_SERVE_r(\d+)\.json$", p)
        if m:
            rounds.append(int(m.group(1)))
    return os.path.join(out_dir, f"BENCH_SERVE_r{max(rounds) + 1:02d}.json")


def main() -> int:
    result = run_bench()
    out = _next_out_path()
    # provenance link: a run manifest (config + device topology +
    # metrics snapshot) next to the artifact, path stamped into the
    # json so the trajectory point traces back to what ran
    mpath = re.sub(r"BENCH_SERVE_r(\d+)\.json$",
                   r"run_manifest_serve_r\1.json", out)
    if mpath == out:
        mpath = out + ".manifest.json"
    try:
        from lightgbm_tpu.obs.manifest import write_manifest

        write_manifest(mpath, extra={
            "bench": "serve", "run_id": result["run_id"],
            "artifact": out,
        })
        result["run_manifest"] = mpath
    except Exception as e:  # noqa: BLE001 — provenance must not kill the bench
        sys.stderr.write(f"[bench_serve] run manifest not written: {e}\n")
    with open(out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    result["artifact"] = out
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
