"""TPU-resident inference & serving subsystem (lightgbm_tpu/serving).

Parity contract: the tensorized device predictor must match the host
walkers within 1e-5 on every model family — regression / binary /
multiclass / ranking, categorical splits, NaN missing values, linear
trees — on models round-tripped through the reference text format.
Compile contract: the bucket-batched dispatcher compiles at most once
per configured bucket across a 100-request mixed-size sequence
(retrace-guard-asserted)."""

import io
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (
    BucketDispatcher,
    MicroBatcher,
    ModelRegistry,
    ScoringServer,
    TensorForest,
)


def _roundtrip(bst):
    """Model -> reference text format -> fresh Booster (the serving
    path always scores LOADED models, so parity is asserted on the
    round-tripped artifact)."""
    return lgb.Booster(model_str=bst.model_to_string())


def _train(params, X, y, rounds=10, **ds_kw):
    ds = lgb.Dataset(X, label=y, free_raw_data=False, **ds_kw)
    p = dict(verbosity=-1, min_data_in_leaf=5)
    p.update(params)
    return lgb.train(p, ds, num_boost_round=rounds)


def _families(rng):
    """(name, booster, scoring matrix) per model family."""
    out = []
    X = rng.randn(1500, 8)
    yreg = X @ rng.randn(8) + 0.1 * rng.randn(1500)
    out.append(("regression",
                _train({"objective": "regression", "num_leaves": 31}, X, yreg),
                rng.randn(400, 8)))

    Xc = rng.randn(1500, 8)
    Xc[:, 3] = rng.randint(0, 12, 1500)
    Xc[rng.rand(1500) < 0.07, 1] = np.nan  # NaN missing type
    yb = (np.nan_to_num(Xc[:, 0]) + (Xc[:, 3] % 3 == 0) > 0.3).astype(float)
    Xq = rng.randn(400, 8)
    Xq[:, 3] = rng.randint(-2, 20, 400)  # incl. unseen/negative cats
    Xq[rng.rand(400) < 0.07, 1] = np.nan
    out.append(("binary+cat+nan",
                _train({"objective": "binary", "num_leaves": 31}, Xc, yb,
                       categorical_feature=[3]),
                Xq))

    ym = rng.randint(0, 3, 1500)
    out.append(("multiclass",
                _train({"objective": "multiclass", "num_class": 3,
                        "num_leaves": 15}, X, ym, rounds=6),
                rng.randn(300, 8)))

    yr = np.clip((X[:, 0] + 0.3 * rng.randn(1500)) * 2 + 2, 0, 4).astype(int)
    group = np.full(30, 50)
    out.append(("lambdarank",
                _train({"objective": "lambdarank", "num_leaves": 15,
                        "min_data_in_leaf": 2}, X, yr, rounds=6,
                       group=group),
                rng.randn(300, 8)))

    Xl = rng.randn(1200, 5)
    yl = Xl[:, 0] * 2 + Xl[:, 1] + 0.1 * rng.randn(1200)
    Xl[rng.rand(1200) < 0.04, 1] = np.nan
    Xlq = rng.randn(300, 5)
    Xlq[rng.rand(300) < 0.04, 1] = np.nan
    dsl = lgb.Dataset(Xl, label=yl, free_raw_data=False,
                      params={"linear_tree": True})
    out.append(("linear_tree",
                lgb.train({"objective": "regression", "num_leaves": 15,
                           "linear_tree": True, "verbosity": -1,
                           "min_data_in_leaf": 5}, dsl, num_boost_round=8),
                Xlq))
    return out


def test_device_predictor_parity_all_families(rng):
    for name, bst, Xq in _families(rng):
        loaded = _roundtrip(bst)
        host = loaded._gbdt.predict_raw(Xq)
        forest = TensorForest.from_booster(loaded)
        dev = forest.predict_raw(Xq)
        np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-5,
                                   err_msg=name)
        # and against the ORIGINAL (non-roundtripped) booster's walk —
        # native when the toolchain exists, numpy level walk otherwise
        np.testing.assert_allclose(dev, bst._gbdt.predict_raw(Xq),
                                   rtol=1e-5, atol=1e-5, err_msg=name)


def test_device_pred_leaf_and_truncation(rng):
    X = rng.randn(1200, 6)
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y, rounds=9)
    forest = TensorForest.from_booster(bst)
    Xq = rng.randn(200, 6)
    np.testing.assert_array_equal(
        forest.predict_leaf(Xq), bst._gbdt.predict_leaf_index(Xq)
    )
    # num_iteration / start_iteration truncation
    for start, num in ((0, 4), (2, 3), (5, -1)):
        np.testing.assert_allclose(
            forest.predict_raw(Xq, start, num),
            bst._gbdt.predict_raw(Xq, start, num),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_array_equal(
            forest.predict_leaf(Xq, start, num),
            bst._gbdt.predict_leaf_index(Xq, start, num),
        )


def test_booster_predict_device_kwarg(rng):
    X = rng.randn(1000, 6)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    np.testing.assert_allclose(
        bst.predict(X[:100], device="tpu"), bst.predict(X[:100]),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        bst.predict(X[:100], device="tpu", raw_score=True),
        bst.predict(X[:100], raw_score=True),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_array_equal(
        bst.predict(X[:100], device="tpu", pred_leaf=True),
        bst.predict(X[:100], pred_leaf=True),
    )


def test_narrow_input_raises_like_host(rng):
    X = rng.randn(800, 6)
    bst = _train({"objective": "regression", "num_leaves": 15}, X, X[:, 5])
    forest = TensorForest.from_booster(bst)
    assert forest.max_feature >= 2
    with pytest.raises(IndexError):
        forest.predict_raw(rng.randn(10, 2))


# ---------------------------------------------------------------- dispatcher
def test_dispatcher_parity_and_chunking(rng):
    X = rng.randn(1500, 6)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    forest = TensorForest.from_booster(bst)
    disp = BucketDispatcher(forest, buckets=(16, 64, 256))
    host = bst._gbdt.predict_raw(X)
    # oversized batch: chunked into max-bucket pieces + a padded tail
    np.testing.assert_allclose(disp.score_raw(X), host,
                               rtol=1e-5, atol=1e-5)
    # 1-row latency path
    np.testing.assert_allclose(disp.score_raw(X[7]), host[:, 7:8],
                               rtol=1e-5, atol=1e-5)
    s = disp.stats()
    assert s["count"] == 2 and s["rows"] == 1501


def test_dispatcher_compiles_bounded_by_buckets(retrace_guard, rng):
    """THE serving compile contract: 100 mixed-size requests, at most
    one compile per configured bucket (analysis/retrace.py guard on
    the real jit entry's trace-cache)."""
    X = rng.randn(2000, 7)
    y = (X[:, 0] + X[:, 2] > 0).astype(float)
    # deliberately odd tree count/size so this forest's table shapes
    # are not already warm in the shared jit cache
    bst = _train({"objective": "binary", "num_leaves": 23}, X, y, rounds=11)
    forest = TensorForest.from_booster(bst)
    buckets = (32, 128, 512)
    disp = BucketDispatcher(forest, buckets=buckets)
    sizes = [int(s) for s in rng.randint(1, 600, 100)]
    with retrace_guard(
        entry_points=[forest.jit_entry],
        max_retraces=len(buckets),
        what="bucket-batched scoring (100 mixed-size requests)",
    ) as rep:
        for n in sizes:
            disp.score_raw(X[:n])
    assert rep.per_entry  # the guard actually saw the entry point
    # warmed up, the same traffic must not compile AT ALL
    with retrace_guard(
        entry_points=[forest.jit_entry], max_retraces=0,
        what="warm bucket-batched scoring",
    ):
        for n in sizes[:20]:
            disp.score_raw(X[:n])


def test_dispatcher_warmup_precompiles(retrace_guard, rng):
    X = rng.randn(600, 5)
    bst = _train({"objective": "regression", "num_leaves": 19}, X, X[:, 0],
                 rounds=7)
    forest = TensorForest.from_booster(bst)
    disp = BucketDispatcher(forest, buckets=(16, 64))
    disp.warmup(num_features=5)
    with retrace_guard(entry_points=[forest.jit_entry], max_retraces=0,
                       what="post-warmup scoring"):
        disp.score_raw(X[:10])
        disp.score_raw(X[:60])


def test_microbatcher_concurrent_submits(rng):
    X = rng.randn(900, 6)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    forest = TensorForest.from_booster(bst)
    disp = BucketDispatcher(forest, buckets=(64, 256))
    mb = MicroBatcher(disp)
    try:
        futs = [mb.submit(X[i * 30: (i + 1) * 30]) for i in range(12)]
        host = bst._gbdt.predict_raw(X[:360])
        for i, f in enumerate(futs):
            got = f.result(timeout=30)  # (n, K)
            np.testing.assert_allclose(
                got.T, host[:, i * 30: (i + 1) * 30], rtol=1e-5, atol=1e-5
            )
    finally:
        mb.close()


def test_sharded_forest_parity(rng):
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    from lightgbm_tpu.parallel.data_parallel import make_mesh

    X = rng.randn(1000, 6)
    y = rng.randint(0, 3, 1000)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "num_leaves": 15}, X, y, rounds=5)
    host = bst._gbdt.predict_raw(X[:320])
    forest = TensorForest.from_booster(bst, mesh=make_mesh())
    assert forest.num_devices == jax.device_count()
    np.testing.assert_allclose(forest.predict_raw(X[:320]), host,
                               rtol=1e-5, atol=1e-5)
    # dispatcher aligns bucket rungs to the mesh
    disp = BucketDispatcher(forest, buckets=(10, 100))
    assert all(b % forest.num_devices == 0 for b in disp.buckets)
    np.testing.assert_allclose(disp.score_raw(X[:37]), host[:, :37],
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------------ registry
def test_registry_load_swap_rollback(rng):
    X = rng.randn(900, 5)
    y = (X[:, 0] > 0).astype(float)
    b1 = _train({"objective": "binary", "num_leaves": 15}, X, y, rounds=6)
    b2 = _train({"objective": "binary", "num_leaves": 15}, X, y, rounds=12)
    reg = ModelRegistry()
    v1 = reg.load("m", b1.model_to_string())
    assert v1 == 1 and reg.models()["m"]["active"] == 1
    np.testing.assert_allclose(reg.predict("m", X[:50]), b1.predict(X[:50]),
                               rtol=1e-5, atol=1e-6)
    v2 = reg.load("m", b2.model_to_string())  # hot-swap activates v2
    assert reg.models()["m"]["active"] == v2
    np.testing.assert_allclose(reg.predict("m", X[:50]), b2.predict(X[:50]),
                               rtol=1e-5, atol=1e-6)
    assert reg.rollback("m") == v1
    np.testing.assert_allclose(reg.predict("m", X[:50]), b1.predict(X[:50]),
                               rtol=1e-5, atol=1e-6)
    # pinned-version scoring regardless of the active pointer
    np.testing.assert_allclose(reg.predict("m", X[:50], version=v2),
                               b2.predict(X[:50]), rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError):
        reg.unload("m", v1)  # active version is protected
    reg.unload("m", v2)
    assert [v["version"] for v in reg.models()["m"]["versions"]] == [v1]
    with pytest.raises(KeyError):
        reg.predict("nope", X[:5])


def test_registry_json_model_roundtrip(rng):
    """dump_model() JSON loads back (model_io.load_model_dict) and
    scores identically — incl. categorical bitsets and missing types."""
    X = rng.randn(1200, 6)
    X[:, 2] = rng.randint(0, 9, 1200)
    X[rng.rand(1200) < 0.05, 4] = np.nan
    y = (np.nan_to_num(X[:, 4]) + (X[:, 2] % 2) > 0.4).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 categorical_feature=[2])
    reg = ModelRegistry()
    reg.load("t", bst.model_to_string())
    reg.load("j", bst.dump_model())
    Xq = rng.randn(200, 6)
    Xq[:, 2] = rng.randint(-1, 12, 200)
    Xq[rng.rand(200) < 0.05, 4] = np.nan
    np.testing.assert_allclose(reg.predict("t", Xq), reg.predict("j", Xq),
                               rtol=0, atol=0)
    np.testing.assert_allclose(reg.predict("j", Xq), bst.predict(Xq),
                               rtol=1e-5, atol=1e-6)


# -------------------------------------------------------------------- server
def test_scoring_server_jsonl_protocol(rng):
    X = rng.randn(800, 5)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    reg = ModelRegistry()
    reg.load("default", bst.model_to_string())
    reqs = [
        {"op": "ping"},
        {"op": "score", "rows": X[:4].tolist()},
        {"op": "score", "rows": X[:4].tolist(), "raw_score": True},
        {"op": "score", "model": "missing", "rows": [[0.0] * 5]},
        {"op": "models"},
        {"op": "stats"},
        {"op": "quit"},
    ]
    sin = io.StringIO("\n".join(json.dumps(r) for r in reqs) + "\n")
    sout = io.StringIO()
    assert ScoringServer(reg).serve(sin, sout) == len(reqs)
    resp = [json.loads(line) for line in sout.getvalue().splitlines()]
    assert resp[0] == {"ok": True, "pong": True}
    np.testing.assert_allclose(resp[1]["pred"], bst.predict(X[:4]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(resp[2]["pred"],
                               bst.predict(X[:4], raw_score=True),
                               rtol=1e-5, atol=1e-6)
    assert not resp[3]["ok"] and "missing" in resp[3]["error"]
    assert resp[4]["models"]["default"]["active"] == 1
    assert resp[5]["stats"]["default"]["count"] >= 2
    assert resp[6]["quit"]
    # bad JSON must produce an error line, not kill the loop
    sout2 = io.StringIO()
    ScoringServer(reg).serve(io.StringIO("not json\n"), sout2)
    assert not json.loads(sout2.getvalue())["ok"]


def test_server_load_and_swap_ops(rng, tmp_path):
    X = rng.randn(700, 4)
    bst = _train({"objective": "regression", "num_leaves": 15}, X, X[:, 0])
    path = tmp_path / "m.txt"
    bst.save_model(str(path))
    reqs = [
        {"op": "load", "model": "m", "path": str(path)},
        {"op": "load", "model": "m", "model_str": bst.model_to_string()},
        {"op": "swap", "model": "m", "version": 1},
        {"op": "rollback", "model": "m"},  # nothing below v1 -> error
        {"op": "score", "model": "m", "rows": X[:2].tolist()},
    ]
    sin = io.StringIO("\n".join(json.dumps(r) for r in reqs) + "\n")
    sout = io.StringIO()
    ScoringServer(ModelRegistry()).serve(sin, sout)
    resp = [json.loads(line) for line in sout.getvalue().splitlines()]
    assert resp[0]["version"] == 1 and resp[1]["version"] == 2
    assert resp[2]["ok"] and resp[2]["active"] == 1
    assert not resp[3]["ok"]
    np.testing.assert_allclose(resp[4]["pred"], bst.predict(X[:2]),
                               rtol=1e-5, atol=1e-6)


def test_latency_stats_counters():
    from lightgbm_tpu.timer import LatencyStats

    ls = LatencyStats(window=8)
    for ms in (1, 2, 3, 4, 100):
        ls.observe(ms / 1e3, rows=10)
    s = ls.snapshot()
    assert s["count"] == 5 and s["rows"] == 50
    assert s["p50_ms"] == pytest.approx(3.0, abs=0.01)
    assert s["p99_ms"] == pytest.approx(100.0, abs=0.01)
    assert s["mean_ms"] == pytest.approx(22.0, abs=0.01)
    ls.reset()
    assert ls.snapshot()["count"] == 0


def test_http_front_end(rng):
    """serve_http: same vocabulary over POST /v1/<op> + health/stats
    GETs, on an ephemeral port."""
    import threading
    import urllib.request

    from lightgbm_tpu.serving import serve_http

    X = rng.randn(600, 4)
    bst = _train({"objective": "regression", "num_leaves": 15}, X, X[:, 0])
    reg = ModelRegistry()
    reg.load("default", bst.model_to_string())
    httpd = serve_http(reg, port=0, block=False)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        def post(path, body):
            req = urllib.request.Request(
                base + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["ok"]
        out = post("/v1/score", {"rows": X[:5].tolist()})
        np.testing.assert_allclose(out["pred"], bst.predict(X[:5]),
                                   rtol=1e-5, atol=1e-6)
        with urllib.request.urlopen(base + "/v1/models", timeout=30) as r:
            assert json.loads(r.read())["models"]["default"]["active"] == 1
        # errors come back as JSON with ok=false, status 400
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/v1/score", {"model": "missing", "rows": [[0.0] * 4]})
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def test_registry_linear_tree_json_roundtrip(rng):
    """dump_model() on a linear-tree model carries the linear-leaf
    extension keys and loads back to identical predictions (a silent
    leaf-const fallback here once shipped wrong scores)."""
    X = rng.randn(1200, 5)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.randn(1200)
    X[rng.rand(1200) < 0.04, 1] = np.nan
    ds = lgb.Dataset(X, label=y, free_raw_data=False,
                     params={"linear_tree": True})
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "linear_tree": True, "verbosity": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=8)
    d = bst.dump_model()
    assert d["tree_info"][0]["is_linear"]
    reg = ModelRegistry()
    reg.load("j", d)
    Xq = rng.randn(200, 5)
    Xq[rng.rand(200) < 0.04, 1] = np.nan
    np.testing.assert_allclose(reg.predict("j", Xq), bst.predict(Xq),
                               rtol=1e-5, atol=1e-5)


def test_registry_pred_leaf_rides_bucket_ladder(retrace_guard, rng):
    """pred_leaf through the registry must use the bucket ladder too —
    mixed-size leaf requests compile at most once per rung."""
    X = rng.randn(1200, 6)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 27}, X, y, rounds=9)
    reg = ModelRegistry(buckets=(32, 128))
    reg.load("m", bst.model_to_string())
    forest = reg._entry("m").forest
    sizes = [int(s) for s in rng.randint(1, 200, 30)]
    with retrace_guard(entry_points=[forest.jit_entry], max_retraces=2,
                       what="pred_leaf mixed sizes"):
        for n in sizes:
            out = reg.predict("m", X[:n], pred_leaf=True)
            np.testing.assert_array_equal(
                out, bst._gbdt.predict_leaf_index(X[:n])
            )


def test_registry_predict_via_queue(rng):
    X = rng.randn(800, 5)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    np.testing.assert_allclose(
        reg.predict("m", X[:40], via_queue=True), bst.predict(X[:40]),
        rtol=1e-5, atol=1e-6,
    )
    # truncated requests bypass the queue but still answer correctly
    np.testing.assert_allclose(
        reg.predict("m", X[:40], via_queue=True, num_iteration=3,
                    raw_score=True),
        bst.predict(X[:40], num_iteration=3, raw_score=True),
        rtol=1e-5, atol=1e-6,
    )


def test_dispatcher_empty_batch(rng):
    X = rng.randn(500, 5)
    bst = _train({"objective": "regression", "num_leaves": 15}, X, X[:, 0])
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    out = reg.predict("m", np.zeros((0, 5)))
    assert out.shape == (0,)
    leaf = reg.predict("m", np.zeros((0, 5)), pred_leaf=True)
    assert leaf.shape == (0, bst.num_trees())


def test_registry_path_named_like_model_string(rng, tmp_path):
    """A model FILE whose path starts with 'tree' must load as a file,
    not be parsed as an inline model string."""
    X = rng.randn(500, 4)
    bst = _train({"objective": "regression", "num_leaves": 7}, X, X[:, 0],
                 rounds=3)
    path = tmp_path / "tree_v2.txt"
    bst.save_model(str(path))
    reg = ModelRegistry()
    reg.load("m", str(path))
    np.testing.assert_allclose(reg.predict("m", X[:10]), bst.predict(X[:10]),
                               rtol=1e-5, atol=1e-6)


def test_unload_closes_microbatcher(rng):
    X = rng.randn(500, 4)
    bst = _train({"objective": "regression", "num_leaves": 7}, X, X[:, 0],
                 rounds=3)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    reg.predict("m", X[:10], via_queue=True)  # lazily creates the batcher
    mv = reg._entry("m")
    assert mv.batcher is not None
    assert all(w.is_alive() for w in mv.batcher._workers)
    reg.unload("m")
    assert not any(w.is_alive() for w in mv.batcher._workers)


def test_serve_buckets_default_matches_dispatch():
    """The ladder is single-sourced in config.DEFAULT_SERVE_BUCKETS
    (dispatch imports it — config is the leaf module, so the reverse
    import would cycle); this pins the re-export so a future literal
    cannot drift."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import DEFAULT_BUCKETS

    assert tuple(Config({}).serve_buckets) == DEFAULT_BUCKETS


def test_registry_warmup_covers_model_width(retrace_guard, rng):
    """Warmup must precompile at the model's DECLARED width, not
    max_feature+1 — a model that never splits its last features would
    otherwise recompile every bucket on the first real batch."""
    X = np.concatenate([rng.randn(600, 1), np.ones((600, 5))], axis=1)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "binary", "num_leaves": 7}, X, y, rounds=5)
    reg = ModelRegistry(buckets=(16, 64), warmup=True)
    reg.load("m", bst.model_to_string())
    forest = reg._entry("m").forest
    assert forest.max_feature + 1 < 6  # the gap this test exists for
    with retrace_guard(entry_points=[forest.jit_entry], max_retraces=0,
                       what="post-warmup full-width scoring"):
        reg.predict("m", X[:10])
        reg.predict("m", X[:60])


def test_threshold_f32_cast_never_rounds_up(rng):
    """pack_forest_tables must cast f64 thresholds to f32 with DIRECTED
    (downward) rounding: a threshold just below an exactly-f32 feature
    value that round-to-nearest would round UP flips that value from
    right (f64 host compare) to left on device — a whole-leaf
    divergence, not 1e-5 noise."""
    X = rng.randn(400, 3)
    y = (X[:, 0] > 0).astype(float)
    bst = _train({"objective": "regression", "num_leaves": 5}, X, y, rounds=1)
    t = bst._gbdt.models[0]
    # hostile root split: f32(1.0 - 1e-12) rounds to exactly 1.0
    t.split_feature[0] = 0
    t.threshold[0] = 1.0 - 1e-12
    t.decision_type[0] = 0  # numerical, no missing handling
    assert np.float32(t.threshold[0]) == np.float32(1.0)
    Xp = np.zeros((3, 3), np.float32)
    Xp[0, 0] = 1.0   # exactly f32, must go RIGHT of the root split
    Xp[1, 0] = 0.5   # well left
    Xp[2, 0] = 2.0   # well right
    host_leaf = t.predict_leaf(Xp.astype(np.float64))
    forest = TensorForest([t], 1)
    dev_leaf = forest.predict_leaf(Xp)[:, 0]
    assert np.array_equal(dev_leaf, host_leaf)
    assert np.abs(
        forest.predict_raw(Xp)[0] - t.predict(Xp.astype(np.float64))
    ).max() < 1e-6
