"""Training flight recorder: one JSONL record per boosting round.

PR 9's observability is point-in-time — a metrics scrape, a span
timeline, one manifest per run. This module adds the LONGITUDINAL
half: while training runs, every boosting round appends one line to a
JSONL stream (the "flight record") carrying

- the round index and wall-clock timestamp,
- per-phase host durations for that round, drained from the same
  timer trace-sink the Chrome-trace recorder reads
  (``boosting.ROUND_PHASES`` on the eager loops, one
  ``round: fused step`` span per iteration on the fused loop),
- train/valid metric values (the learning curve — the reference's
  ``record_evaluation`` callback output, but always on),
- per-class tree stats: leaves / depth / best split gain / a
  finite-leaf flag (NaN poisoning is visible the round it happens),
- gradient/hessian norm summaries (eager loops only; the fused loop's
  gradients never leave the device),
- chunk-level throughput (trees/s over the dispatched chunk).

Enabled through the ``record_file=`` config/CLI param (engine.train
owns the lifecycle). The stream is the substrate two consumers build
on: ``obs.anomaly`` sentinels watch it live, and ``obs.aggregate``
merges per-process streams host-side for the multihost trainer.

The recorder is exception-safe by construction: every line is written
and flushed before the sentinels see the record, and ``close()`` (run
from engine.train's ``finally``) detaches the timer sink and closes
the file even when training aborts mid-round — the JSONL tail stays
parseable and the run manifest picks up the final summary
(``last_summary()``).

Host-side only; nothing here runs inside jit.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import timer as _timer

SCHEMA = "lightgbm-tpu/flight-record/v1"

# module-global summary of the most recently closed recorder, so the
# run manifest (written later, possibly by cli.py's finally block) can
# fold the flight record in without holding a recorder reference
_last_lock = threading.Lock()
_last_summary: Optional[Dict[str, Any]] = None


def last_summary() -> Optional[Dict[str, Any]]:
    """Summary dict of the most recently closed FlightRecorder in this
    process (None if none closed yet). Consumed by obs.manifest."""
    with _last_lock:
        return dict(_last_summary) if _last_summary else None


def _set_last_summary(summary: Dict[str, Any]) -> None:
    global _last_summary
    with _last_lock:
        _last_summary = dict(summary)


def clear_last_summary() -> None:
    """Drop the published summary. engine.train calls this when a run
    WITHOUT a recorder starts, so a manifest written after that run
    cannot misattribute an earlier run's flight record (path, rounds,
    anomaly trips) to it."""
    global _last_summary
    with _last_lock:
        _last_summary = None


def tree_stats(trees) -> List[Dict[str, Any]]:
    """Per-tree stats for one round's K class-trees (host ``Tree``
    objects): leaves / depth / best gain / finite-leaf flag. The
    NaN/Inf flag is what the anomaly ``nan_leaf`` sentinel reads."""
    out: List[Dict[str, Any]] = []
    for t in trees:
        lv = np.asarray(t.leaf_value, np.float64)
        gain = np.asarray(t.split_gain, np.float64)
        out.append({
            "leaves": int(t.num_leaves),
            "depth": int(t.max_depth()),
            "best_gain": float(gain.max()) if gain.size else 0.0,
            "leaf_finite": bool(np.isfinite(lv).all()),
        })
    return out


class FlightRecorder:
    """Streams one JSONL record per boosting round; thread-safe.

    ``path=None`` runs the recorder in memory only (the anomaly
    sentinels still consume records; nothing is written) — that is the
    ``anomaly_policy != off`` without ``record_file`` configuration.

    ``resume_bytes`` (checkpoint/resume, docs/RESILIENCE.md) truncates
    an existing stream back to that byte offset — the size the training
    checkpoint captured after its round's record was flushed — and
    appends, so a resumed run's record file carries each round exactly
    once with no torn tail and no duplicated header.
    """

    def __init__(self, path: Optional[str] = None,
                 resume_bytes: Optional[int] = None):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        self._phases: Dict[str, List[float]] = {}
        self._attached = False
        self._closed = False
        self.rounds = 0
        self.last_record: Optional[Dict[str, Any]] = None
        self._t0 = time.time()
        self._anomalies: Dict[str, int] = {}
        if path:
            import os

            if resume_bytes is not None and os.path.exists(path):
                self._fh = open(path, "r+")
                self._fh.truncate(int(resume_bytes))
                self._fh.seek(0, 2)  # append after the surviving records
                self._fh.flush()
            else:
                self._fh = open(path, "w")
                header = {"schema": SCHEMA, "created_unix": self._t0}
                self._fh.write(json.dumps(header) + "\n")
                self._fh.flush()

    # ------------------------------------------------------- phase sink
    def attach(self) -> "FlightRecorder":
        """Subscribe to the timer's span stream (additive — the Chrome
        trace recorder keeps its own slot)."""
        if not self._attached:
            _timer.add_trace_sink(self._on_span)
            self._attached = True
        return self

    def _on_span(self, name: str, start_s: float, dur_s: float) -> None:
        with self._lock:
            self._phases.setdefault(name, []).append(dur_s)

    def drain_phases(self) -> Dict[str, List[float]]:
        """Spans observed since the last drain, name -> durations in
        observation order (the engine slices the fused loop's per-round
        ``round: fused step`` spans out of a chunk-level drain)."""
        with self._lock:
            out = self._phases
            self._phases = {}
        return out

    # ---------------------------------------------------------- records
    def record(self, rec: Dict[str, Any]) -> None:
        """Append one round record (written + flushed immediately so an
        abort mid-train never loses the rounds that already ran)."""
        with self._lock:
            if self._closed:
                return
            self.rounds += 1
            self.last_record = rec
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()

    def note_anomaly(self, kind: str) -> None:
        """Sentinel trips fold into the recorder summary (the manifest
        then carries the per-kind counts)."""
        with self._lock:
            self._anomalies[kind] = self._anomalies.get(kind, 0) + 1

    # ---------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "schema": SCHEMA,
                "path": self.path,
                "rounds": self.rounds,
                "wall_s": round(time.time() - self._t0, 3),
            }
            if self._anomalies:
                out["anomalies"] = dict(self._anomalies)
            last = self.last_record
        if last and last.get("evals"):
            out["last_evals"] = dict(last["evals"])
        return out

    def close(self) -> Dict[str, Any]:
        """Detach the timer sink, flush and close the stream; safe to
        call twice and safe mid-exception (engine.train's finally).
        Returns the summary it published for the manifest."""
        if self._attached:
            _timer.remove_trace_sink(self._on_span)
            self._attached = False
        with self._lock:
            if not self._closed:
                self._closed = True
                if self._fh is not None:
                    try:
                        self._fh.flush()
                        self._fh.close()
                    finally:
                        self._fh = None
        s = self.summary()
        _set_last_summary(s)
        return s


def read_stream(path: str) -> List[Dict[str, Any]]:
    """Load a flight-record JSONL back into a list of round records
    (the header line is skipped). Round-trip partner of ``record``."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("schema") == SCHEMA:
                continue  # stream header
            out.append(rec)
    return out
