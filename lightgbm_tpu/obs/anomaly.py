"""Anomaly sentinels over the flight-record stream.

The recorder (obs/recorder.py) turns a training run into a stream of
per-round records; this module WATCHES that stream and acts on it —
the step from "we can measure" to "the system notices". Four
sentinels, each cheap enough to run on every round:

- ``nan_metric`` — any train/valid metric value is NaN/Inf;
- ``nan_leaf`` — a freshly-materialized tree carries non-finite leaf
  values (``tree_stats``'s ``leaf_finite`` flag);
- ``loss_spike`` — a lower-is-better metric exceeds ``spike_ratio`` x
  its rolling-window median (divergence: huge learning rate, poisoned
  gradients). Higher-is-better metrics are covered by the NaN check
  only — their collapse is a modelling question, not a runtime fault;
- ``throughput_collapse`` — chunk trees/s falls below
  ``collapse_frac`` x the rolling median (a wedged device, a
  background compile storm, a degraded interconnect);
- ``dead_rounds`` — ``max_dead_rounds`` consecutive rounds where no
  class-tree found a positive-gain split (the model stopped learning
  but the loop keeps burning chip time).

Policy (``anomaly_policy`` config/CLI param):

- ``off``  — sentinels don't run;
- ``warn`` — each trip logs a warning, increments
  ``lgbmtpu_anomaly_trips_total{kind}`` and emits a trace instant
  event (visible in the Perfetto timeline at the round it happened);
- ``abort`` — same, then raises :class:`AnomalyAbort`. The engine
  flushes the flight recorder in its ``finally`` and lets the typed
  exception propagate, so the JSONL tail and the run manifest survive
  the abort (regression-tested);
- ``rollback`` — raises like ``abort``, but engine.train catches it
  and, when a ``snapshot_freq`` checkpoint exists, restores the last
  good round and retrains (optionally with a shrunken learning_rate,
  ``anomaly_rollback_lr_decay``) instead of discarding the run —
  docs/RESILIENCE.md "Recovery policies". Without a checkpoint it
  degrades to ``abort``.

Host-side only; consumes plain dict records, never device values.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from .. import log

POLICIES = ("off", "warn", "abort", "rollback")


class AnomalyAbort(RuntimeError):
    """Typed abort raised under ``anomaly_policy=abort``: carries the
    sentinel kind, the tripping round, and a human-readable detail."""

    def __init__(self, kind: str, round_idx: int, detail: str):
        super().__init__(
            f"anomaly sentinel {kind!r} tripped at round {round_idx}: "
            f"{detail}"
        )
        self.kind = kind
        self.round_idx = round_idx
        self.detail = detail


def _finite(v: Any) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return True  # non-numeric values are not this sentinel's job


class AnomalySentinel:
    """Stateful checker; feed it every round record via :meth:`check`."""

    def __init__(
        self,
        policy: str = "warn",
        *,
        spike_window: int = 8,
        spike_ratio: float = 2.0,
        spike_min_rounds: int = 3,
        collapse_window: int = 8,
        collapse_frac: float = 0.25,
        collapse_min_chunks: int = 3,
        max_dead_rounds: int = 10,
        recorder: Optional[Any] = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"anomaly_policy must be one of {POLICIES}, got {policy!r}"
            )
        self.policy = policy
        self.spike_window = int(spike_window)
        self.spike_ratio = float(spike_ratio)
        self.spike_min_rounds = int(spike_min_rounds)
        self.collapse_frac = float(collapse_frac)
        self.collapse_min_chunks = int(collapse_min_chunks)
        self.max_dead_rounds = int(max_dead_rounds)
        self.recorder = recorder
        self.trips: List[Dict[str, Any]] = []
        self._loss_hist: Dict[str, Deque[float]] = {}
        self._tps_hist: Deque[float] = deque(maxlen=int(collapse_window))
        self._dead_streak = 0

    # ------------------------------------------------------------- trip
    def _trip(self, kind: str, round_idx: int, detail: str) -> None:
        self.trips.append(
            {"kind": kind, "round": round_idx, "detail": detail}
        )
        if self.recorder is not None:
            self.recorder.note_anomaly(kind)
        from .metrics import default_registry

        reg = default_registry()
        if reg.enabled:
            reg.counter(
                "lgbmtpu_anomaly_trips_total",
                "anomaly sentinel trips over the training flight record",
                labels=("kind",),
            ).inc(1, kind=kind)
        from . import tracing

        rec = tracing.active()
        if rec is not None:
            rec.add_instant(
                f"anomaly: {kind}",
                {"round": round_idx, "detail": detail},
            )
        log.warning(f"anomaly[{kind}] at round {round_idx}: {detail}")
        if self.policy in ("abort", "rollback"):
            # rollback rides the same typed raise: engine.train owns the
            # checkpoint-restore decision, not the sentinel
            raise AnomalyAbort(kind, round_idx, detail)

    # ------------------------------------------------------------ check
    def check(self, rec: Dict[str, Any]) -> None:
        """Inspect one round record; raises AnomalyAbort under the
        abort policy. Under ``warn`` every tripped sentinel fires (one
        record can trip several kinds)."""
        if self.policy == "off":
            return
        it = int(rec.get("round", -1))
        evals = rec.get("evals") or {}

        # --- NaN/Inf in metric values
        bad = sorted(k for k, v in evals.items() if not _finite(v))
        if bad:
            self._trip(
                "nan_metric", it,
                f"non-finite metric value(s) {bad}",
            )

        # --- NaN/Inf in freshly-materialized leaf values
        trees = rec.get("trees") or []
        poisoned = [
            i for i, t in enumerate(trees)
            if not t.get("leaf_finite", True)
        ]
        if poisoned:
            self._trip(
                "nan_leaf", it,
                f"non-finite leaf values in class tree(s) {poisoned}",
            )

        # --- loss spike over the rolling median (lower-better metrics:
        # the eval key carries higher_better in rec["evals_hb"])
        hb = rec.get("evals_hb") or {}
        for key, v in evals.items():
            if hb.get(key, False) or not _finite(v):
                continue
            hist = self._loss_hist.setdefault(
                key, deque(maxlen=self.spike_window)
            )
            if len(hist) >= self.spike_min_rounds:
                med = sorted(hist)[len(hist) // 2]
                if med > 0 and float(v) > self.spike_ratio * med:
                    self._trip(
                        "loss_spike", it,
                        f"{key}={float(v):.6g} > {self.spike_ratio}x "
                        f"rolling median {med:.6g}",
                    )
            hist.append(float(v))

        # --- throughput collapse vs the rolling median of chunk tps
        tps = rec.get("trees_per_sec")
        if tps is not None and _finite(tps) and float(tps) > 0:
            if len(self._tps_hist) >= self.collapse_min_chunks:
                h = sorted(self._tps_hist)
                med = h[len(h) // 2]
                if med > 0 and float(tps) < self.collapse_frac * med:
                    self._trip(
                        "throughput_collapse", it,
                        f"{float(tps):.4g} trees/s < "
                        f"{self.collapse_frac}x rolling median "
                        f"{med:.4g}",
                    )
            self._tps_hist.append(float(tps))

        # --- dead (zero-gain) rounds
        if trees:
            dead = all(
                t.get("leaves", 1) <= 1 or t.get("best_gain", 0.0) <= 0.0
                for t in trees
            )
            self._dead_streak = self._dead_streak + 1 if dead else 0
            if self._dead_streak >= self.max_dead_rounds:
                streak = self._dead_streak
                self._dead_streak = 0  # re-arm after the trip
                self._trip(
                    "dead_rounds", it,
                    f"{streak} consecutive rounds without a "
                    "positive-gain split",
                )

    def summary(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for t in self.trips:
            counts[t["kind"]] = counts.get(t["kind"], 0) + 1
        return {"policy": self.policy, "trips": counts}


def make_sentinel(policy: str,
                  recorder: Optional[Any] = None
                  ) -> Optional[AnomalySentinel]:
    """Config hook: None for ``off`` (zero per-round overhead),
    otherwise a sentinel wired to the recorder's anomaly counters."""
    if policy == "off":
        return None
    return AnomalySentinel(policy, recorder=recorder)
