"""Fleet-level metric aggregation — host-side, no jax collectives.

Multi-host training (parallel/multihost.py) and multi-replica serving
run one metrics registry PER PROCESS. This module merges those views
into one fleet picture using only host-side transport — snapshot
files on a shared filesystem, or HTTP pulls from each worker's
``/metrics`` endpoint — deliberately NOT jax collectives: the CPU
backend used by tier-1 has no cross-process collectives
(docs/DESIGN_DECISIONS.md, the xfail'd multihost tests), and
observability must keep working exactly when the training fabric is
the thing that broke.

Three sources, one merged shape:

- ``write_snapshot(path)`` / ``read_snapshot(path)`` — one process
  dumps its registry (samples WITH metric kinds, schema below);
- ``pull_snapshot(url)`` — scrape a worker's Prometheus ``/metrics``
  endpoint and parse the text exposition back into the same shape;
- ``merge(snapshots)`` — fold N snapshots into a fleet view: counter
  and histogram samples SUM across processes (fleet totals — wire
  bytes, trips, request counts), gauge samples sum too with per-key
  ``min``/``max`` ride-alongs (fleet trees/s is the sum of per-worker
  trees/s; the min/max spread is how a straggler shows up).

Recorder streams merge the same way: ``merge_recorder_streams``
zips per-process flight records by round (lockstep training writes
one record per round per process) into per-round fleet rows.

Rendered for humans by ``tools/obs_report.py``; consumed
programmatically by ``parallel.multihost.merged_fleet_snapshot``.
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request
from typing import Any, Dict, Iterable, List, Optional, Sequence

SCHEMA = "lightgbm-tpu/metrics-snapshot/v1"

# kinds whose samples are additive across processes; gauges are summed
# too but annotated with min/max so stragglers stay visible
_SUMMED_KINDS = ("counter", "histogram")


def snapshot_dict(registry=None, process: Optional[int] = None
                  ) -> Dict[str, Any]:
    """One process's registry as a JSON-serializable snapshot (samples
    keyed by rendered label string, kind preserved per metric).

    With an explicit ``process`` this is jax-free — the serving
    gateway (``task=gateway``, a pure host process) snapshots its own
    registry without dragging the device runtime in; only the
    ``process=None`` default asks jax for the process index."""
    from .metrics import _render_labels, default_registry

    reg = registry if registry is not None else default_registry()
    metrics: Dict[str, Dict[str, Any]] = {}
    for s in reg.samples():
        fam = metrics.setdefault(
            s.name, {"kind": s.kind, "help": s.help, "values": {}}
        )
        fam["values"][_render_labels(s.labels)] = float(s.value)
    if process is None:
        try:
            import jax

            process = jax.process_index()
        except Exception:  # noqa: BLE001 — snapshot must not need a backend
            process = 0
    return {"schema": SCHEMA, "process": int(process), "metrics": metrics}


def write_snapshot(path: str, registry=None,
                   process: Optional[int] = None) -> Dict[str, Any]:
    snap = snapshot_dict(registry, process)
    with open(path, "w") as f:
        json.dump(snap, f, indent=2, sort_keys=True)
        f.write("\n")
    return snap


def read_snapshot(path: str) -> Dict[str, Any]:
    with open(path) as f:
        snap = json.load(f)
    if snap.get("schema") != SCHEMA:
        raise ValueError(
            f"{path} is not a metrics snapshot (schema "
            f"{snap.get('schema')!r} != {SCHEMA!r})"
        )
    return snap


# ---------------------------------------------------- prometheus pull
_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$"
)


def parse_prometheus(text: str, process: int = 0) -> Dict[str, Any]:
    """Text exposition (format 0.0.4) -> the snapshot shape above.
    Histogram component samples (_bucket/_sum/_count) keep their full
    sample name; the family kind comes from the # TYPE line."""
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    metrics: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            kinds[fam] = kind.strip()
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            fam, _, h = rest.partition(" ")
            helps[fam] = h
            continue
        if line.startswith("#"):
            continue
        m = _PROM_SAMPLE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in kinds:
                fam = name[: -len(suffix)]
                break
        entry = metrics.setdefault(name, {
            "kind": kinds.get(fam, "untyped"),
            "help": helps.get(fam, ""),
            "values": {},
        })
        entry["values"][labels] = float(value)
    return {"schema": SCHEMA, "process": int(process), "metrics": metrics}


def pull_snapshot(url: str, timeout: float = 10.0,
                  process: int = 0, retries: int = 2) -> Dict[str, Any]:
    """HTTP-scrape one worker's ``/metrics`` endpoint (the serving
    transport's route, server.py) into a snapshot.

    Transient transport failures (connection refused mid-restart, a
    scrape racing server startup) retry with backoff; an HTTP error
    status is a real answer from a live server and fails immediately
    (retrying a 404 would just repeat it)."""
    from ..resilience.backoff import retry_call

    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"

    def _pull() -> Dict[str, Any]:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return parse_prometheus(r.read().decode(), process=process)

    return retry_call(
        _pull,
        retries=retries,
        base_s=0.25,
        retry_on=(urllib.error.URLError, OSError),
        retriable=lambda e: not isinstance(e, urllib.error.HTTPError),
        describe=f"scrape {url}",
    )


# --------------------------------------------------------------- merge
def merge(snapshots: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-process snapshots into one fleet view."""
    merged: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        for name, fam in (snap.get("metrics") or {}).items():
            out = merged.setdefault(name, {
                "kind": fam.get("kind", "untyped"),
                "help": fam.get("help", ""),
                "values": {},
                "min": {},
                "max": {},
            })
            for key, v in (fam.get("values") or {}).items():
                v = float(v)
                out["values"][key] = out["values"].get(key, 0.0) + v
                out["min"][key] = min(out["min"].get(key, v), v)
                out["max"][key] = max(out["max"].get(key, v), v)
    for fam in merged.values():
        if fam["kind"] in _SUMMED_KINDS:
            # additive families need no spread annotations
            fam.pop("min")
            fam.pop("max")
    return {
        "schema": SCHEMA + "+merged",
        "processes": len(snapshots),
        "metrics": merged,
    }


def merge_files(paths: Iterable[str]) -> Dict[str, Any]:
    return merge([read_snapshot(p) for p in sorted(paths)])


def render_merged(merged: Dict[str, Any]) -> str:
    """A merged snapshot back to text exposition (format 0.0.4) — the
    gateway's single-pane ``/metrics``: one scrape body covering the
    gateway process plus every live backend. Gauge min/max spreads are
    dropped (Prometheus has no native spread sample; the JSON view
    keeps them)."""
    lines: List[str] = []
    metrics = merged.get("metrics") or {}
    for name in sorted(metrics):
        fam = metrics[name]
        kind = fam.get("kind", "untyped")
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {kind}")
        for key in sorted(fam.get("values") or {}):
            v = fam["values"][key]
            vs = str(int(v)) if float(v).is_integer() else repr(float(v))
            lines.append(f"{name}{key} {vs}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------- recorder streams
def merge_recorder_streams(
    streams: Sequence[List[Dict[str, Any]]]
) -> List[Dict[str, Any]]:
    """Zip per-process flight-record streams by round index into fleet
    rows. Lockstep data-parallel training produces identical metric
    values on every rank (the collective makes them so) — the merged
    row keeps rank 0's evals and annotates disagreement; throughput
    sums; per-phase durations keep the fleet max (the straggler bound,
    which is what a lockstep collective actually waits on)."""
    by_round: Dict[int, List[Dict[str, Any]]] = {}
    for stream in streams:
        for rec in stream:
            by_round.setdefault(int(rec.get("round", -1)), []).append(rec)
    out: List[Dict[str, Any]] = []
    for rnd in sorted(by_round):
        recs = by_round[rnd]
        row: Dict[str, Any] = {"round": rnd, "processes": len(recs)}
        evals = [r.get("evals") for r in recs if r.get("evals")]
        if evals:
            row["evals"] = dict(evals[0])
            drift = {
                k for e in evals[1:] for k, v in e.items()
                if abs(float(v) - float(evals[0].get(k, v))) > 1e-9
            }
            if drift:
                # lockstep broke: ranks disagree on the metric value —
                # surface it, never average it away
                row["evals_disagree"] = sorted(drift)
        tps = [float(r["trees_per_sec"]) for r in recs
               if r.get("trees_per_sec")]
        if tps:
            row["trees_per_sec"] = sum(tps)
        phases: Dict[str, float] = {}
        for r in recs:
            for name, dur in (r.get("phases") or {}).items():
                phases[name] = max(phases.get(name, 0.0), float(dur))
        if phases:
            row["phases_max"] = phases
        out.append(row)
    return out
