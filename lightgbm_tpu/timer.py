"""Named per-phase accumulating timers (the reference's USE_TIMETAG
subsystem: Timer/FunctionTimer, utils/common.h:979-1043, global_timer
printed at exit, per-phase instrumentation across the tree learner and
network layers — SURVEY §5).

TPU adaptation: phases are HOST-side regions (dispatch, collect,
binning, eval). Device work inside jit is asynchronous, so a scope that
must include device completion passes `block=True` to synchronize
before stopping the clock (used by bench/profilers, off in production
paths). Scopes also enter `jax.profiler.TraceAnnotation`-compatible
`jax.named_scope` so traces collected with jax.profiler line up with
the same names.

Enable summary-at-exit with env LIGHTGBM_TPU_TIMETAG=1 (the analog of
the reference's compile-time USE_TIMETAG), or call
`global_timer.print_summary()` directly.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional


class Timer:
    """Accumulating named stopwatches (reference utils/common.h:979)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._cnt: Dict[str, int] = {}
        self.enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

    @contextmanager
    def scope(self, name: str, block: bool = False) -> Iterator[None]:
        """Time a region; with block=True waits for device completion
        (jax.block_until_ready on nothing — a full device sync) before
        stopping, so the region includes its dispatched work."""
        if not self.enabled:
            yield
            return
        import jax

        t0 = time.perf_counter()
        with jax.named_scope(name.replace(" ", "_")):
            yield
        if block:
            try:
                (jax.device_put(0) + 0).block_until_ready()
            except Exception:  # noqa: BLE001 — never break the timed path
                pass
        dt = time.perf_counter() - t0
        self._acc[name] = self._acc.get(name, 0.0) + dt
        self._cnt[name] = self._cnt.get(name, 0) + 1

    def summary(self) -> Dict[str, tuple]:
        return {
            k: (self._acc[k], self._cnt[k])
            for k in sorted(self._acc, key=lambda k: -self._acc[k])
        }

    def print_summary(self) -> None:
        """common.h:1012 — per-phase totals at exit."""
        from . import log

        if not self._acc:
            return
        log.info("LightGBM-TPU phase timings:")
        for name, (acc, cnt) in self.summary().items():
            log.info(f"  {name}: {acc:.3f}s ({cnt} calls)")

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()


global_timer = Timer()

if global_timer.enabled:
    atexit.register(global_timer.print_summary)


class LatencyStats:
    """Latency/throughput counters for serving paths.

    Unlike Timer scopes (accumulating host-region stopwatches for
    training phases), serving needs DISTRIBUTION statistics — a p99
    regression hides completely in an accumulated total. Keeps a ring
    of the most recent `window` request latencies plus lifetime count /
    row totals; `snapshot()` derives mean/p50/p95/p99 over the ring and
    rows/sec over the lifetime. Thread-safe: the serving server and the
    microbatch worker observe from different threads.
    """

    def __init__(self, window: int = 2048) -> None:
        self._window = int(window)
        self._ring: List[float] = []
        self._pos = 0
        self._count = 0
        self._rows = 0
        self._total_s = 0.0
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def observe(self, seconds: float, rows: int = 1) -> None:
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(float(seconds))
            else:
                self._ring[self._pos] = float(seconds)
                self._pos = (self._pos + 1) % self._window
            self._count += 1
            self._rows += int(rows)
            self._total_s += float(seconds)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            ring = sorted(self._ring)
            count, rows, total = self._count, self._rows, self._total_s
            uptime = time.perf_counter() - self._t0

        def pct(p: float) -> float:
            if not ring:
                return 0.0
            return ring[min(len(ring) - 1, int(p * (len(ring) - 1) + 0.5))]

        # mean over the same ring the percentiles cover — a lifetime
        # mean would stay inflated by cold-start outliers forever and
        # read as mean >> p99 on a warmed-up server
        mean = sum(ring) / len(ring) if ring else 0.0
        return {
            "count": count,
            "rows": rows,
            "mean_ms": round(1e3 * mean, 4),
            "p50_ms": round(1e3 * pct(0.50), 4),
            "p95_ms": round(1e3 * pct(0.95), 4),
            "p99_ms": round(1e3 * pct(0.99), 4),
            "rows_per_sec": round(rows / uptime, 2) if uptime > 0 else 0.0,
            "busy_frac": round(total / uptime, 4) if uptime > 0 else 0.0,
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pos = 0
            self._count = 0
            self._rows = 0
            self._total_s = 0.0
            self._t0 = time.perf_counter()


_latency: Dict[str, LatencyStats] = {}
_latency_lock = threading.Lock()


def latency_stats(name: str) -> LatencyStats:
    """Named process-global LatencyStats (one per serving entry point,
    mirroring global_timer's named-scope registry)."""
    with _latency_lock:
        if name not in _latency:
            _latency[name] = LatencyStats()
        return _latency[name]


def latency_summary() -> Dict[str, Dict[str, float]]:
    with _latency_lock:
        return {k: v.snapshot() for k, v in sorted(_latency.items())}
