"""End-to-end smoke: train, improve metric, predict, save/load round-trip."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import make_synthetic_binary, make_synthetic_regression


def test_train_binary_improves_auc():
    X, y = make_synthetic_binary(2000, 10)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "metric": "auc", "verbosity": -1, "min_data_in_leaf": 5},
        ds,
        num_boost_round=30,
        valid_sets=[ds],
        valid_names=["train"],
    )
    pred = bst.predict(X)
    assert pred.shape == (2000,)
    assert np.all((pred >= 0) & (pred <= 1))
    from sklearn.metrics import roc_auc_score

    auc = roc_auc_score(y, pred)
    assert auc > 0.95, f"AUC too low: {auc}"


def test_train_regression_decreases_l2():
    X, y = make_synthetic_regression(2000, 10)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    res = {}
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "learning_rate": 0.1,
         "metric": "l2", "verbosity": -1},
        ds,
        num_boost_round=50,
        valid_sets=[ds],
        valid_names=["train"],
        callbacks=[lgb.record_evaluation(res)],
    )
    l2 = res["train"]["l2"]
    assert l2[-1] < l2[0] * 0.2, f"l2 did not decrease enough: {l2[0]} -> {l2[-1]}"
    # training-score predictions equal fresh predictions
    pred = bst.predict(X)
    mse = np.mean((pred - y) ** 2)
    assert abs(mse - l2[-1]) < 1e-3 * max(1.0, abs(l2[-1]))


def test_model_save_load_roundtrip(tmp_path):
    X, y = make_synthetic_binary(500, 8)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1}, ds, num_boost_round=10
    )
    pred = bst.predict(X)
    f = tmp_path / "model.txt"
    bst.save_model(f)
    bst2 = lgb.Booster(model_file=str(f))
    pred2 = bst2.predict(X)
    np.testing.assert_allclose(pred, pred2, rtol=1e-6, atol=1e-9)


def test_early_stopping():
    X, y = make_synthetic_binary(2000, 10)
    Xt, yt = X[:1500], y[:1500]
    Xv, yv = X[1500:], y[1500:]
    dtrain = lgb.Dataset(Xt, label=yt, free_raw_data=False)
    dvalid = lgb.Dataset(Xv, label=yv, reference=dtrain, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "learning_rate": 0.3,
         "metric": "binary_logloss", "verbosity": -1},
        dtrain,
        num_boost_round=200,
        valid_sets=[dvalid],
        callbacks=[lgb.early_stopping(5, verbose=False)],
    )
    assert bst.best_iteration > 0
    assert bst.best_iteration <= 200


def test_constant_label_keeps_bias_tree():
    """All-stump first iteration: the boost-from-average constant tree
    survives the async pipeline's stop detection (gbdt.cpp:429-441)."""
    X = np.random.RandomState(0).randn(600, 4)
    y = np.full(600, 3.5)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        ds, num_boost_round=5,
    )
    assert bst.num_trees() == 1
    np.testing.assert_allclose(bst.predict(X[:3]), 3.5, rtol=1e-6)


def test_training_stops_when_unsplittable():
    """min_data_in_leaf too large for any split after a few iterations ->
    training truncates at the first dead iteration, and the model equals
    its own score (predictions consistent)."""
    X, y = make_synthetic_regression(300, 5)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "min_data_in_leaf": 160},  # only the root has >= 160 rows... no split
        ds, num_boost_round=60,
    )
    # boost-from-average constant tree only
    assert bst.num_trees() <= 1
    pred = bst.predict(X)
    np.testing.assert_allclose(pred, np.mean(y), rtol=1e-5)


def test_nonzero_mean_target_fast_path():
    """Boost-from-average bias must not be double-counted on the async
    fast path (score gets it once at BoostFromAverage; only the stored
    tree carries it) — regression test for a mean-10 target."""
    rs = np.random.RandomState(3)
    X = rs.randn(1500, 8).astype(np.float32)
    y = (10.0 + X[:, 0] * 0.5 + rs.randn(1500) * 0.1).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "learning_rate": 0.2,
         "verbosity": -1},
        ds, num_boost_round=30,
    )
    pred = bst.predict(X)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.5, f"RMSE {rmse} — boost-from-average bias double-counted?"
    # internal training score must equal the stored-model prediction
    internal = bst._gbdt.get_score(bst._gbdt.train)[0]
    np.testing.assert_allclose(internal, bst.predict(X, raw_score=True), rtol=1e-4, atol=1e-4)
