"""sklearn-estimator tests (reference tests/python_package_test/test_sklearn.py)."""

import numpy as np
import pytest

from conftest import make_synthetic_binary, make_synthetic_regression

import lightgbm_tpu as lgb


def test_regressor_basic():
    X, y = make_synthetic_regression(n=600, n_features=8)
    model = lgb.LGBMRegressor(n_estimators=20, num_leaves=15, verbosity=-1)
    model.fit(X, y)
    pred = model.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < np.var(y) * 0.5
    assert model.n_features_ == 8
    assert len(model.feature_importances_) == 8
    assert model.feature_importances_.sum() > 0


def test_classifier_binary():
    X, y = make_synthetic_binary(n=600, n_features=8)
    model = lgb.LGBMClassifier(n_estimators=20, num_leaves=15, verbosity=-1)
    model.fit(X, y)
    proba = model.predict_proba(X)
    assert proba.shape == (600, 2)
    assert np.allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    pred = model.predict(X)
    acc = float(np.mean(pred == y))
    assert acc > 0.85
    assert set(model.classes_) == {0.0, 1.0}
    assert model.n_classes_ == 2


def test_classifier_multiclass():
    rs = np.random.RandomState(3)
    X = rs.randn(600, 6)
    y = np.argmax(X[:, :3] + 0.3 * rs.randn(600, 3), axis=1)
    model = lgb.LGBMClassifier(n_estimators=15, num_leaves=7, verbosity=-1)
    model.fit(X, y)
    assert model.n_classes_ == 3
    proba = model.predict_proba(X)
    assert proba.shape == (600, 3)
    acc = float(np.mean(model.predict(X) == y))
    assert acc > 0.8


def test_classifier_string_labels():
    X, y = make_synthetic_binary(n=400, n_features=6)
    labels = np.where(y > 0, "pos", "neg")
    model = lgb.LGBMClassifier(n_estimators=10, num_leaves=7, verbosity=-1)
    model.fit(X, labels)
    pred = model.predict(X)
    assert set(np.unique(pred)) <= {"pos", "neg"}
    acc = float(np.mean(pred == labels))
    assert acc > 0.8


def test_early_stopping_via_eval_set():
    X, y = make_synthetic_regression(n=800, n_features=8)
    Xt, yt = X[:600], y[:600]
    Xv, yv = X[600:], y[600:]
    model = lgb.LGBMRegressor(n_estimators=100, num_leaves=15, verbosity=-1)
    model.fit(
        Xt, yt,
        eval_set=[(Xv, yv)],
        callbacks=[lgb.early_stopping(5, verbose=False)],
    )
    assert model.best_iteration_ > 0
    assert "valid_0" in model.evals_result_
    assert "l2" in model.evals_result_["valid_0"]


def test_ranker():
    rs = np.random.RandomState(7)
    n, q = 500, 25
    X = rs.randn(n, 6)
    rel = np.clip((X[:, 0] * 2 + rs.randn(n)).astype(int) % 4, 0, 3)
    group = np.full(q, n // q)
    model = lgb.LGBMRanker(n_estimators=10, num_leaves=7, verbosity=-1)
    model.fit(X, rel, group=group)
    pred = model.predict(X)
    assert pred.shape == (n,)
    # scores should correlate with relevance
    assert np.corrcoef(pred, rel)[0, 1] > 0.3


def test_ranker_requires_group():
    X, y = make_synthetic_regression(n=100, n_features=4)
    model = lgb.LGBMRanker(n_estimators=5)
    with pytest.raises(ValueError):
        model.fit(X, y)


def test_custom_objective_callable():
    X, y = make_synthetic_regression(n=400, n_features=6)

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    model = lgb.LGBMRegressor(n_estimators=15, num_leaves=15, objective=l2_obj, verbosity=-1)
    model.fit(X, y)
    pred = model.predict(X)
    # raw score (no convert): still should fit the data
    assert float(np.mean((pred - y) ** 2)) < np.var(y) * 0.6


def test_custom_eval_metric():
    X, y = make_synthetic_binary(n=400, n_features=6)

    def my_err(y_true, y_pred):
        p = 1.0 / (1.0 + np.exp(-y_pred))
        return "my_err", float(np.mean((p > 0.5) != y_true)), False

    model = lgb.LGBMClassifier(n_estimators=10, num_leaves=7, verbosity=-1)
    model.fit(X, y, eval_set=[(X, y)], eval_metric=my_err)
    assert "my_err" in model.evals_result_["valid_0"]


def test_sklearn_param_mapping():
    X, y = make_synthetic_regression(n=300, n_features=6)
    model = lgb.LGBMRegressor(
        n_estimators=5, reg_alpha=0.1, reg_lambda=0.2, min_child_samples=5,
        subsample=0.8, subsample_freq=1, colsample_bytree=0.8, random_state=11,
    )
    model.fit(X, y)
    cfg = model.booster_.config
    assert cfg.lambda_l1 == pytest.approx(0.1)
    assert cfg.lambda_l2 == pytest.approx(0.2)
    assert cfg.min_data_in_leaf == 5
    assert cfg.bagging_fraction == pytest.approx(0.8)
    assert cfg.feature_fraction == pytest.approx(0.8)


def test_clone_and_get_params():
    from sklearn.base import clone

    model = lgb.LGBMRegressor(n_estimators=7, num_leaves=9, custom_thing=3)
    params = model.get_params()
    assert params["n_estimators"] == 7
    assert params["custom_thing"] == 3
    m2 = clone(model)
    assert m2.get_params()["num_leaves"] == 9
