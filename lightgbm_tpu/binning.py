"""Host-side feature binning (BinMapper).

Replicates the behavior of the reference binning front-end
(include/LightGBM/bin.h:85-259 BinMapper, src/io/bin.cpp GreedyFindBin /
FindBin): per-feature value->bin mapping with at most `max_bin` bins built
from sampled values, zero-as-one-bin splitting, missing-value handling
(None / Zero / NaN, bin.h:27), and categorical bins ordered by count.

Binning runs on host (numpy) once per dataset; the resulting bin matrix is
what lives on TPU. This mirrors the reference where binning is a CPU
preprocessing step even for the CUDA backend.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# reference: include/LightGBM/bin.h kZeroThreshold
K_ZERO_THRESHOLD = 1e-35
K_SPARSE_THRESHOLD = 0.8
K_MISSING_ZERO = -1  # placeholder


class MissingType(enum.IntEnum):
    # reference bin.h:27 enum MissingType
    NONE = 0
    ZERO = 1
    NAN = 2


class BinType(enum.IntEnum):
    # reference bin.h BinType
    NUMERICAL = 0
    CATEGORICAL = 1


def _check_double_equal_ordered(a: float, b: float) -> bool:
    """Common::CheckDoubleEqualOrdered (common.h:851): b <= nextafter(a)."""
    return b <= np.nextafter(a, np.inf)


def greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Build <=max_bin upper bounds over sorted distinct values.

    Bit-exact mirror of src/io/bin.cpp:80 GreedyFindBin (verified by the
    first-tree structure parity test against the built reference CLI):
    small-cardinality features get one bin per distinct value (merging
    ones below min_data_in_bin); otherwise a greedy equal-mass packing
    where any value holding >= mean bin mass gets its own bin. Bounds
    are nextafter-nudged midpoints (Common::GetDoubleUpperBound) with
    ordered-equality dedup.
    """
    num_distinct = len(distinct_values)
    bub: List[float] = []
    if num_distinct == 0:
        return [float("inf")]
    if num_distinct > 512:
        # the pure-Python greedy loop costs ~110 ms per 200k distinct
        # values; the native library is the same double arithmetic in
        # C++ (bit-exact — asserted by the binning parity tests)
        from . import native

        nb = native.greedy_find_bin(
            np.asarray(distinct_values, np.float64),
            np.asarray(counts, np.int64),
            max_bin, total_cnt, min_data_in_bin,
        )
        if nb is not None:
            return [float(v) for v in nb]
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += int(counts[i])
            if cur_cnt_inbin >= min_data_in_bin:
                val = float(np.nextafter(
                    (float(distinct_values[i]) + float(distinct_values[i + 1]))
                    / 2.0, np.inf,
                ))
                if not bub or not _check_double_equal_ordered(bub[-1], val):
                    bub.append(val)
                    cur_cnt_inbin = 0
        bub.append(float("inf"))
        return bub

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(np.sum(is_big))
    rest_sample_cnt = total_cnt - int(np.sum(counts[is_big]))
    mean_bin_size = (
        rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else float("inf")
    )
    uppers = [float("inf")] * max_bin
    lowers = [float("inf")] * max_bin
    bin_cnt = 0
    lowers[0] = float(distinct_values[0])
    cur_cnt_inbin = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt_inbin += int(counts[i])
        # need a new bin: current value is big, accumulated enough mass, or
        # next value is big and we have at least half a mean bin
        if (
            is_big[i]
            or cur_cnt_inbin >= mean_bin_size
            # reference bin.cpp:132 writes `mean_bin_size * 0.5f`, but
            # C++ promotes the float literal to double — plain 0.5 here;
            # np.float32(0.5) would compute the product in f32 under
            # NumPy-2 weak promotion and diverge from the reference
            or (is_big[i + 1]
                and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))
        ):
            uppers[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lowers[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            # only bins closed on NON-big values consume the rest budget
            # (big values pre-paid theirs in the scan above)
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = (
                    rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0
                    else float("inf")
                )
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = float(np.nextafter((uppers[i] + lowers[i + 1]) / 2.0, np.inf))
        if not bub or not _check_double_equal_ordered(bub[-1], val):
            bub.append(val)
    bub.append(float("inf"))
    return bub


def find_bin_bounds(
    values: np.ndarray,
    total_sample_cnt: int,
    max_bin: int,
    min_data_in_bin: int,
    zero_as_one_bin: bool = True,
) -> List[float]:
    """FindBin semantics (src/io/bin.cpp BinMapper::FindBin numerical path).

    `values` are the sampled *non-missing* values; zeros that were omitted
    from sampling are accounted via total_sample_cnt - len(values) (the
    reference samples only non-zero values and infers the zero count).
    Zero gets its own bin: the value range is split at +-kZeroThreshold and
    bins are found separately on the negative and positive parts.
    """
    values = np.asarray(values, dtype=np.float64)
    zero_cnt = int(total_sample_cnt - len(values))
    neg = values[values < -K_ZERO_THRESHOLD]
    pos = values[values > K_ZERO_THRESHOLD]
    zero_cnt += int(len(values) - len(neg) - len(pos))

    if not zero_as_one_bin:
        dv, cnt = np.unique(values, return_counts=True)
        return greedy_find_bin(dv, cnt, max_bin, total_sample_cnt, min_data_in_bin)

    # FindBinWithZeroAsOneBin (bin.cpp:246), kept branch-for-branch:
    # the zero bin exists whenever a positive side exists (kZeroThreshold
    # bound pushed unconditionally before the right-side bounds), and the
    # left budget is left_cnt_data / (total - zeros) * (max_bin - 1)
    left_cnt_data = len(neg)
    right_cnt_data = len(pos)
    if left_cnt_data + right_cnt_data + zero_cnt == 0:
        return [float("inf")]

    bounds: List[float] = []
    if left_cnt_data > 0 and max_bin > 1:
        denom = total_sample_cnt - zero_cnt
        left_max_bin = max(
            1, int(left_cnt_data / max(denom, 1) * (max_bin - 1))
        )
        dv, cnt = np.unique(neg, return_counts=True)
        bounds = greedy_find_bin(
            dv, cnt, left_max_bin, left_cnt_data, min_data_in_bin
        )
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD
    right_max_bin = max_bin - 1 - len(bounds)
    if right_cnt_data > 0 and right_max_bin > 0:
        dv, cnt = np.unique(pos, return_counts=True)
        right_bounds = greedy_find_bin(
            dv, cnt, right_max_bin, right_cnt_data, min_data_in_bin
        )
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(float("inf"))
    return bounds


def load_forced_bins(path: str,
                     num_total_features: Optional[int] = None
                     ) -> Dict[int, List[float]]:
    """Parse a forcedbins_filename JSON file (reference
    src/io/dataset_loader.cpp DatasetLoader::GetForcedBins; example
    format examples/regression/forced_bins.json): a list of
    ``{"feature": idx, "bin_upper_bound": [floats]}`` entries ->
    feature index -> forced upper bounds. Missing file is fatal (an
    explicitly configured path that silently does nothing is the bug
    this satellite removes); malformed entries warn and are skipped."""
    import json
    import os

    from . import log

    if not path:
        return {}
    if not os.path.exists(path):
        log.fatal(f"forcedbins_filename {path} does not exist")
    try:
        entries = json.loads(open(path).read())
    except json.JSONDecodeError as e:
        log.fatal(f"forcedbins_filename {path} is not valid JSON: {e}")
    if not isinstance(entries, list):
        log.fatal(
            f"forcedbins_filename {path} must contain a JSON LIST of "
            '{"feature": idx, "bin_upper_bound": [...]} entries, got '
            f"{type(entries).__name__}"
        )
    out: Dict[int, List[float]] = {}
    for e in entries:
        try:
            f = int(e["feature"])
            bounds = [float(b) for b in e["bin_upper_bound"]]
        except (KeyError, TypeError, ValueError):
            log.warning(f"forced bins entry {e!r} malformed; skipped")
            continue
        if num_total_features is not None and not 0 <= f < num_total_features:
            log.warning(
                f"forced bins feature {f} out of range "
                f"[0, {num_total_features}); skipped"
            )
            continue
        if bounds:
            out[f] = bounds
    return out


def find_bin_bounds_forced(
    values: np.ndarray,
    total_sample_cnt: int,
    max_bin: int,
    min_data_in_bin: int,
    forced: Sequence[float],
) -> List[float]:
    """Bin bounds honoring forced boundaries (reference bin.cpp
    FindBinWithPredefinedBin semantics): every forced bound becomes a
    mandatory bin edge; the remaining budget is split over the
    inter-bound segments in proportion to their sample mass, with the
    greedy packer running inside each segment.

    Deviation (documented): the zero-as-one-bin split is bypassed on
    forced features — the user's explicit boundaries define the
    partition instead of the automatic +-kZeroThreshold split.
    """
    forced_u = sorted({float(b) for b in forced if np.isfinite(b)})
    if not forced_u:
        return find_bin_bounds(values, total_sample_cnt, max_bin,
                               min_data_in_bin)
    budget = max(max_bin - 1, 1)
    if len(forced_u) > budget:
        from . import log

        # an explicitly configured bound must never vanish silently —
        # same contract as load_forced_bins' malformed-entry warnings
        log.warning(
            f"forced bins: {len(forced_u)} bounds exceed the "
            f"max_bin={max_bin} budget; keeping the {budget} smallest"
        )
        forced_u = forced_u[:budget]
    values = np.asarray(values, np.float64)
    # sparse sampling omits implicit zeros from `values` (the CSC path
    # passes explicit entries only); their mass belongs to whichever
    # segment contains 0.0 — both for budget shares and for the greedy
    # packer's total/min_data_in_bin accounting
    zero_cnt = max(int(total_sample_cnt - len(values)), 0)
    edges = [-np.inf] + forced_u + [np.inf]
    rest = max(max_bin - len(forced_u), 1)
    n_total = max(len(values) + zero_cnt, 1)
    out: List[float] = []
    for i in range(len(edges) - 1):
        lo, hi = edges[i], edges[i + 1]
        seg = values[(values > lo) & (values <= hi)]
        seg_zero = zero_cnt if (lo < 0.0 <= hi) else 0
        mass = len(seg) + seg_zero
        sub = max(1, int(round(rest * mass / n_total)))
        if mass:
            dv, cnt = np.unique(seg, return_counts=True)
            if seg_zero:
                j = int(np.searchsorted(dv, 0.0))
                if j < len(dv) and dv[j] == 0.0:
                    cnt[j] += seg_zero
                else:
                    dv = np.insert(dv, j, 0.0)
                    cnt = np.insert(cnt, j, seg_zero)
            sb = greedy_find_bin(dv, cnt, sub, mass, min_data_in_bin)
        else:
            sb = [float("inf")]
        if np.isfinite(hi):
            sb[-1] = hi  # the forced bound closes this segment
        for b in sb:
            if not out or not _check_double_equal_ordered(out[-1], b):
                out.append(b)
    if not out or not np.isposinf(out[-1]):
        out.append(float("inf"))
    if len(out) > max_bin:  # segment rounding overflow: keep forced
        keep = set(forced_u)
        extra = [b for b in out[:-1] if b not in keep]
        extra = extra[: max(max_bin - 1 - len(forced_u), 0)]
        out = sorted(set(extra) | keep) + [float("inf")]
    return out


@dataclass
class BinMapper:
    """Per-feature value->bin mapping (reference bin.h:85)."""

    upper_bounds: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    bin_type: BinType = BinType.NUMERICAL
    missing_type: MissingType = MissingType.NONE
    categories: Tuple[int, ...] = ()  # bin index -> category value
    num_bin: int = 1
    most_freq_bin: int = 0
    default_bin: int = 0  # bin of value 0.0 (GetDefaultBin)
    is_trivial: bool = True  # single bin -> feature unused
    min_value: float = 0.0
    max_value: float = 0.0
    _cat_to_bin: Optional[Dict[int, int]] = None

    @staticmethod
    def from_sample(
        values: np.ndarray,
        total_sample_cnt: int,
        max_bin: int,
        min_data_in_bin: int = 3,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        bin_type: BinType = BinType.NUMERICAL,
        min_data_per_group: int = 100,
        max_cat_threshold: int = 32,
        forced_bounds: Optional[Sequence[float]] = None,
    ) -> "BinMapper":
        values = np.asarray(values, dtype=np.float64).ravel()
        na_cnt = int(np.sum(np.isnan(values)))
        clean = values[~np.isnan(values)]

        if bin_type == BinType.CATEGORICAL:
            if forced_bounds:
                from . import log

                log.warning(
                    "forced bins only apply to numerical features; "
                    "ignored for a categorical feature"
                )
            return BinMapper._categorical(
                clean, na_cnt, total_sample_cnt, max_bin, use_missing
            )

        # missing type resolution (reference FindBin :120-160)
        if not use_missing:
            missing_type = MissingType.NONE
        elif zero_as_missing:
            missing_type = MissingType.ZERO
        elif na_cnt > 0:
            missing_type = MissingType.NAN
        else:
            missing_type = MissingType.NONE

        if missing_type == MissingType.NAN:
            eff_max_bin = max_bin - 1  # reserve last bin for NaN
        else:
            eff_max_bin = max_bin
            if missing_type == MissingType.NONE and na_cnt > 0:
                # NaNs treated as zero when use_missing=false
                clean = np.concatenate([clean, np.zeros(na_cnt)])
                na_cnt = 0

        eff_total = total_sample_cnt - (
            na_cnt if missing_type == MissingType.NAN else 0
        )
        if forced_bounds:
            bounds = find_bin_bounds_forced(
                clean, eff_total, eff_max_bin, min_data_in_bin,
                forced_bounds,
            )
        else:
            bounds = find_bin_bounds(
                clean, eff_total, eff_max_bin, min_data_in_bin,
            )
        ub = np.asarray(bounds, dtype=np.float64)
        num_bin = len(ub)
        if missing_type == MissingType.NAN:
            num_bin += 1  # trailing NaN bin

        m = BinMapper(
            upper_bounds=ub,
            bin_type=BinType.NUMERICAL,
            missing_type=missing_type,
            num_bin=num_bin,
            is_trivial=(num_bin <= 1),
            min_value=float(np.min(clean)) if len(clean) else 0.0,
            max_value=float(np.max(clean)) if len(clean) else 0.0,
        )
        m.default_bin = int(np.searchsorted(ub, 0.0, side="left"))
        # most_freq_bin from the sample histogram
        if len(clean):
            sample_bins = m.values_to_bins(clean)
            zero_extra = total_sample_cnt - len(clean) - na_cnt
            bc = np.bincount(sample_bins, minlength=m.num_bin).astype(np.int64)
            if zero_extra > 0:
                bc[m.default_bin] += zero_extra
            m.most_freq_bin = int(np.argmax(bc))
        return m

    @staticmethod
    def _categorical(
        clean: np.ndarray,
        na_cnt: int,
        total_sample_cnt: int,
        max_bin: int,
        use_missing: bool,
    ) -> "BinMapper":
        # reference FindBin categorical path: categories sorted by count desc,
        # keep up to max_bin-1 (cut categories covering <0.1% at the tail),
        # bin 0 holds the most frequent category; negative values -> NaN-ish.
        ints = clean.astype(np.int64)
        neg_mask = ints < 0
        if np.any(neg_mask):
            na_cnt += int(np.sum(neg_mask))
            ints = ints[~neg_mask]
        cats, cnts = np.unique(ints, return_counts=True)
        order = np.argsort(-cnts, kind="stable")
        cats, cnts = cats[order], cnts[order]
        keep = min(len(cats), max_bin - 1 if (use_missing and na_cnt > 0) else max_bin)
        # drop ultra-rare tail categories (reference cuts cumulative 99% + cnt>=2 logic simplified)
        cats, cnts = cats[:keep], cnts[:keep]
        missing_type = MissingType.NAN if (use_missing and na_cnt > 0) else MissingType.NONE
        num_bin = len(cats) + (1 if missing_type == MissingType.NAN else 0)
        m = BinMapper(
            upper_bounds=np.array([np.inf]),
            bin_type=BinType.CATEGORICAL,
            missing_type=missing_type,
            categories=tuple(int(c) for c in cats),
            num_bin=max(1, num_bin),
            is_trivial=(num_bin <= 1),
            min_value=float(cats.min()) if len(cats) else 0.0,
            max_value=float(cats.max()) if len(cats) else 0.0,
        )
        m._cat_to_bin = {int(c): i for i, c in enumerate(cats)}
        m.most_freq_bin = 0
        m.default_bin = m._cat_to_bin.get(0, 0)
        return m

    # ---- value -> bin ----
    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (reference bin.h:161)."""
        values = np.asarray(values, dtype=np.float64).ravel()
        if self.bin_type == BinType.CATEGORICAL:
            out = np.zeros(len(values), dtype=np.int32)
            nan_bin = self.num_bin - 1 if self.missing_type == MissingType.NAN else 0
            c2b = self._cat_to_bin or {}
            ints = np.where(np.isnan(values), -1, values).astype(np.int64)
            # vectorized dict lookup
            if c2b:
                keys = np.fromiter(c2b.keys(), dtype=np.int64)
                vals = np.fromiter(c2b.values(), dtype=np.int32)
                sorter = np.argsort(keys)
                keys, vals = keys[sorter], vals[sorter]
                idx = np.searchsorted(keys, ints)
                idx = np.clip(idx, 0, len(keys) - 1)
                found = keys[idx] == ints
                out = np.where(found, vals[idx], nan_bin).astype(np.int32)
            out[ints < 0] = nan_bin
            return out
        nan_target = (
            self.num_bin - 1 if self.missing_type == MissingType.NAN
            else self.default_bin
        )
        if len(values) > (1 << 15):
            from . import native

            out = native.values_to_bins(values, self.upper_bounds, nan_target)
            if out is not None:
                return out
        nan_mask = np.isnan(values)
        vv = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(self.upper_bounds, vv, side="left").astype(np.int32)
        n_numeric_bins = len(self.upper_bounds)
        bins = np.clip(bins, 0, n_numeric_bins - 1)
        bins[nan_mask] = nan_target
        return bins

    def bin_to_value(self, bin_idx: int) -> float:
        """Threshold bin -> real split value (BinToValue; model files store
        real thresholds and predict with `value <= threshold`)."""
        if self.bin_type == BinType.CATEGORICAL:
            if 0 <= bin_idx < len(self.categories):
                return float(self.categories[bin_idx])
            return float("nan")
        n = len(self.upper_bounds)
        b = min(int(bin_idx), n - 1)
        ub = float(self.upper_bounds[b])
        if np.isinf(ub) and ub > 0:
            return float(self.max_value)
        return ub

    @property
    def nan_bin(self) -> int:
        return self.num_bin - 1 if self.missing_type == MissingType.NAN else -1

    def feature_info_str(self) -> str:
        """feature_infos entry for the text model format
        (gbdt_model_text.cpp: `[min:max]` numerical, `cat:cat:...` categorical,
        `none` for trivial)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BinType.CATEGORICAL:
            return ":".join(str(c) for c in self.categories)
        return f"[{self.min_value:g}:{self.max_value:g}]"
